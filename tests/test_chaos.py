"""Chaos drills for the fault-tolerant DCN session layer
(parallel/dcn.py failure model): gateway kill+restart, partitions that
heal, wire corruption, half-open-slot fencing, heartbeat liveness — each
driven deterministically through utils/faults.py or direct gateway
surgery.  The randomized long-haul version is tools/chaos_soak.py; its
SyntheticActor doubles as this suite's fleet driver so every scenario
short of the real-learner run executes in seconds without jax."""

import json
import socket
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.parallel.dcn import (
    T_CLOCK, T_HELLO, T_TICK, DcnClient, DcnDisconnected, DcnGateway,
    RemoteClock, _recv_frame, _send_frame,
)
from pytorch_distributed_tpu.utils.faults import (
    FaultInjector, InjectedCrash, InjectedDisconnect, parse_faults,
)
from tools.chaos_soak import ChunkLog, SyntheticActor, soak, tagged_transition


@pytest.fixture()
def plane():
    """Gateway + its learner-plane fixtures, chunk deliveries tag-logged."""
    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    log = ChunkLog()
    gw = DcnGateway(store, clock, stats, put_chunk=log,
                    host="127.0.0.1", port=0)
    holder = {"gw": gw}
    yield holder, store, clock, stats, log
    holder["gw"].close()


def _client(gw, slot=0, **kw):
    kw.setdefault("heartbeat_interval", 0)  # drills drive RPCs explicitly
    kw.setdefault("reconnect_timeout", 10.0)
    return DcnClient(("127.0.0.1", gw.port), process_ind=slot, **kw)


class TestFaultInjector:
    def test_parse_and_fire(self):
        inj = FaultInjector(parse_faults("delay@1:0.01,sever@2,corrupt@3"))
        assert inj.frame(b"a") == b"a"          # frame 0: clean
        assert inj.frame(b"b") == b"b"          # frame 1: delayed only
        with pytest.raises(InjectedDisconnect):
            inj.frame(b"c")                     # frame 2
        assert inj.frame(b"dd") != b"dd"        # frame 3: corrupted
        assert inj.injected == 3

    def test_crash_is_not_a_connection_error(self):
        inj = FaultInjector.scripted("crash@0")
        with pytest.raises(InjectedCrash) as ei:
            inj.frame()
        assert not isinstance(ei.value, ConnectionError)

    def test_bad_specs_rejected(self):
        with pytest.raises(ValueError):
            parse_faults("sever")
        with pytest.raises(ValueError):
            parse_faults("teleport@3")

    def test_random_is_reproducible(self):
        a = FaultInjector.random(42)
        b = FaultInjector.random(42)
        assert a._by_frame == b._by_frame
        assert FaultInjector.random(43)._by_frame != a._by_frame

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("DCN_FAULTS_CLIENT", "sever@7")
        inj = FaultInjector.from_env("client")
        assert inj._by_frame == {7: [("sever", 0.0)]}
        monkeypatch.delenv("DCN_FAULTS_CLIENT")
        assert FaultInjector.from_env("client")._by_frame == {}


class TestReconnect:
    def test_transparent_reconnect_after_gateway_restart(self, plane):
        holder, store, clock, stats, log = plane
        gw = holder["gw"]
        client = _client(gw)
        inc0 = client.incarnation
        gw.close()
        holder["gw"] = gw2 = DcnGateway(
            store, clock, stats, put_chunk=log,
            host="127.0.0.1", port=gw.port)
        # the tick rides through: redial, re-HELLO, retransmit — the
        # caller never sees the blip
        client.tick(actor_steps=5)
        assert clock.actor_step.value == 5
        assert client.reconnects == 1
        assert client.incarnation > inc0
        assert gw2.active_slots == {0: client.incarnation}
        assert not client.disconnected.is_set()
        client.close()

    def test_unacked_chunk_resent_after_sever(self, plane):
        holder, *_rest, log = plane
        gw = holder["gw"]
        # frame 0 is HELLO; frame 1 (the first EXP) dies before hitting
        # the wire — the reconnect must re-HELLO (fencing its own
        # half-open predecessor) and retransmit that one chunk
        client = _client(gw, faults=FaultInjector.scripted("sever@1"))
        client.send_chunk([(tagged_transition(99), None)])
        assert log.tags == [99]
        assert client.reconnects == 1
        # the predecessor is either fenced (HELLO beat its FIN) or was
        # already reaped — either way the new incarnation owns the slot;
        # deterministic fencing is pinned by the two-claimant tests
        assert gw.active_slots == {0: client.incarnation}
        client.close()

    def test_corrupt_frame_rejected_then_resent(self, plane):
        holder, *_rest, log = plane
        gw = holder["gw"]
        client = _client(gw, faults=FaultInjector.scripted("corrupt@1"))
        client.send_chunk([(tagged_transition(7), None)])
        # the gateway must never decode garbage into the replay plane:
        # it drops the connection, the client retransmits clean
        assert log.tags == [7]
        assert client.reconnects == 1
        client.close()

    def test_blackhole_partition_then_heal(self, plane):
        holder, *_rest, log = plane
        gw = holder["gw"]
        client = _client(
            gw, faults=FaultInjector.scripted("blackhole@1:0.4"))
        t0 = time.monotonic()
        client.send_chunk([(tagged_transition(1), None)])
        assert time.monotonic() - t0 >= 0.4  # stalled through the outage
        assert log.tags == [1]
        assert client.reconnects == 1
        client.close()

    def test_terminal_disconnect_raises_nonzero_path(self, plane):
        holder, *_rest = plane
        gw = holder["gw"]
        client = _client(gw, reconnect_timeout=0.8)
        rclock = RemoteClock(client, flush_every=10 ** 9)
        gw.close()  # and never comes back
        rclock._pending = 37
        t0 = time.monotonic()
        rclock.flush()  # swallows the terminal error, keeps the steps
        assert time.monotonic() - t0 >= 0.7
        assert client.disconnected.is_set()
        assert not client.stop.is_set()   # a blip is NOT "learner said stop"
        assert rclock._pending == 37      # actor-steps not silently lost
        assert rclock.done(steps=10 ** 9)
        with pytest.raises(DcnDisconnected):
            client.tick(actor_steps=1)    # latched: fast-fail, no redial
        client.close()

    def test_poison_frame_goes_terminal_not_livelock(self, plane):
        """A frame the gateway can NEVER accept (every retransmit
        corrupted) must exhaust the retransmit cap and raise terminally
        — not redial/resend forever with each cycle granting a fresh
        reconnect budget."""
        holder, *_rest = plane
        gw = holder["gw"]
        spec = ",".join(f"corrupt@{i}" for i in range(1, 12))
        client = _client(gw, faults=FaultInjector.scripted(spec))
        with pytest.raises(DcnDisconnected, match="poison"):
            client.send_chunk([(tagged_transition(1), None)])
        assert client.disconnected.is_set()
        client.close()

    def test_injected_crash_propagates_uncaught(self, plane):
        holder, *_rest = plane
        gw = holder["gw"]
        client = _client(gw, faults=FaultInjector.scripted("crash@1"))
        with pytest.raises(InjectedCrash):
            client.tick(actor_steps=1)
        client.close()


class TestWireHardening:
    def test_retransmitted_tick_not_double_counted(self, plane):
        """A tick whose ack was lost is resent verbatim after reconnect;
        the gateway's per-slot seq high-water must count it exactly once
        (actor_step gates the learner's max_replay_ratio throttle)."""
        holder, _store, clock, _stats, _log = plane
        gw = holder["gw"]
        s = socket.create_connection(("127.0.0.1", gw.port))
        try:
            _send_frame(s, T_HELLO, json.dumps(
                {"role": "actor", "process_ind": 0,
                 "incarnation": 1}).encode())
            assert _recv_frame(s)[0] == T_CLOCK
            tick = json.dumps({"actor_steps": 40, "seq": 9,
                               "stats": {"nepisodes": 2.0}}).encode()
            _send_frame(s, T_TICK, tick)
            _recv_frame(s)
            _send_frame(s, T_TICK, tick)  # the retransmit: same bytes
            _recv_frame(s)
            assert clock.actor_step.value == 40
            _send_frame(s, T_TICK, json.dumps(
                {"actor_steps": 2, "seq": 10}).encode())
            _recv_frame(s)
            assert clock.actor_step.value == 42  # fresh seq still counts
        finally:
            s.close()

    def test_malformed_hello_drops_connection_cleanly(self, plane):
        """A JSON-valid HELLO with wrong-typed fields must drop the
        connection like any other malformed frame — not kill the serve
        thread with an uncaught TypeError."""
        holder, *_ = plane
        gw = holder["gw"]
        s = socket.create_connection(("127.0.0.1", gw.port))
        try:
            _send_frame(s, T_HELLO, json.dumps(
                {"role": "actor", "process_ind": "not-a-slot"}).encode())
            with pytest.raises(ConnectionError):
                while True:
                    _recv_frame(s)
        finally:
            s.close()
        assert gw.active_slots == {}
        survivor = _client(gw, slot=1)  # gateway still fully serviceable
        survivor.tick(actor_steps=1)
        survivor.close()


class TestHeartbeat:
    def test_idle_heartbeat_keeps_clock_fresh(self, plane):
        holder, _store, clock, *_rest = plane
        gw = holder["gw"]
        client = _client(gw, heartbeat_interval=0.15)
        clock.set_learner_step(77)
        deadline = time.monotonic() + 5
        while client.learner_step != 77:  # no explicit RPC from us
            assert time.monotonic() < deadline
            time.sleep(0.02)
        client.close()

    def test_heartbeat_reconnects_through_gateway_restart(self, plane):
        holder, store, clock, stats, log = plane
        gw = holder["gw"]
        client = _client(gw, heartbeat_interval=0.15)
        gw.close()
        holder["gw"] = gw2 = DcnGateway(
            store, clock, stats, put_chunk=log,
            host="127.0.0.1", port=gw.port)
        # no main-thread RPC at all: the heartbeat alone must discover
        # the death and re-establish the session + slot claim
        deadline = time.monotonic() + 10
        while not gw2.active_slots:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # the gateway-side claim is visible before the heartbeat thread
        # returns from its HELLO and bumps the counter — poll, don't assert
        while client.reconnects < 1:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert gw2.active_slots == {0: client.incarnation}
        client.close()

    def test_gateway_idle_deadline_reaps_frozen_peer(self):
        clock = GlobalClock()
        gw = DcnGateway(ParamStore(8), clock, ActorStats(),
                        put_chunk=lambda items: None,
                        host="127.0.0.1", port=0, idle_deadline=0.4)
        try:
            frozen = _client(gw, slot=4)  # heartbeats off = frozen actor
            assert gw.active_slots == {4: frozen.incarnation}
            deadline = time.monotonic() + 5
            while gw.active_slots:  # reaped without any disconnect event
                assert time.monotonic() < deadline
                time.sleep(0.05)
            # the freed slot is reclaimable by a replacement process
            fresh = _client(gw, slot=4)
            assert gw.active_slots == {4: fresh.incarnation}
            fresh.close()
            frozen.close()
        finally:
            gw.close()


class TestChaosFleet:
    """The acceptance drill: a fleet of session-layer actors rides
    through a gateway kill+restart with zero abandoned slots, fenced
    re-claims, resent unacked chunks, and no duplicate-slot skew."""

    def test_gateway_restart_mid_run_zero_lost(self, plane):
        holder, store, clock, stats, log = plane
        gw = holder["gw"]
        fleet = [SyntheticActor(("127.0.0.1", gw.port), slot=i, pace=0.001,
                                client_kwargs=dict(
                                    heartbeat_interval=0.25,
                                    reconnect_timeout=10.0)).start()
                 for i in range(3)]
        deadline = time.monotonic() + 10
        while len(log.tags) < 30:  # fleet is demonstrably flowing
            assert time.monotonic() < deadline
            time.sleep(0.01)
        gw.close()  # kill the gateway mid-run...
        holder["gw"] = gw2 = DcnGateway(
            store, clock, stats, put_chunk=log,
            host="127.0.0.1", port=gw.port)
        deadline = time.monotonic() + 20
        while set(gw2.active_slots) != {0, 1, 2}:  # ...everyone re-claims
            assert time.monotonic() < deadline
            time.sleep(0.02)
        marker = len(log.tags)
        while len(log.tags) < marker + 30:  # and keeps delivering
            assert time.monotonic() < deadline
            time.sleep(0.01)
        clock.set_learner_step(10)
        clock.stop.set()
        for a in fleet:
            a.thread.join(15)
            assert not a.thread.is_alive()
            assert a.outcome == "stopped"  # zero abandoned slots
            assert a.client.reconnects >= 1
            assert a.step_regressions == 0
        # at-least-once delivery: every acked chunk arrived (duplicates
        # allowed, loss is not), and no foreign slots ever appeared
        seen = log.seen()
        for a in fleet:
            missing = [t for t in a.acked_tags if t not in seen]
            assert missing == []
        # clean closes free the slots — asynchronously: T_BYE is processed
        # on the gateway's serve thread after the actor thread has joined
        deadline = time.monotonic() + 10
        while gw2.active_slots:
            assert time.monotonic() < deadline
            time.sleep(0.02)

    def test_duplicate_slot_race_single_winner(self, plane):
        """Two claimants race one slot: exactly one wins, and the loser's
        exit does not free the winner's claim (the identity-checked
        release)."""
        holder, *_rest = plane
        gw = holder["gw"]
        a = _client(gw, slot=3, incarnation=100, reconnect_timeout=0.5)
        b = _client(gw, slot=3, incarnation=200)  # fences a's claim
        assert gw.fenced == 1
        assert gw.active_slots == {3: 200}
        b.tick(actor_steps=2)  # winner fully functional
        # loser's reconnect arrives with incarnation 101 < 200: refused,
        # terminally — a live duplicate can never steal the slot back
        with pytest.raises(ConnectionError):
            a.tick(actor_steps=1)
        assert a.disconnected.is_set()
        a.close()
        time.sleep(0.2)  # a's departure must not disturb b's claim
        assert gw.active_slots == {3: 200}
        b.tick(actor_steps=1)
        b.close()

    def test_soak_smoke_no_violations(self):
        """Short randomized soak (the tools/chaos_soak.py entry point):
        seeded wire faults + one gateway restart cycle, zero invariant
        violations."""
        report = soak(seconds=3.0, actors=2, seed=7, restart_every=1.2,
                      reconnect_timeout=10.0, verbose=False)
        assert report["violations"] == []
        assert report["gateway_restarts"] >= 1
        assert report["delivered_chunks"] >= report["acked_chunks"] > 0


class _FlakyTickClient:
    """RemoteClock satellite regression: tick raises once, then works."""

    def __init__(self):
        self.stop = threading.Event()
        self.disconnected = threading.Event()
        self.learner_step = 0
        self.failures = 1
        self.ticked = []

    def tick(self, actor_steps=0, stats=None):
        if self.failures:
            self.failures -= 1
            raise ConnectionError("transient")
        self.ticked.append(actor_steps)
        return self.learner_step


def test_remote_clock_flush_restores_steps_on_failure():
    client = _FlakyTickClient()
    rclock = RemoteClock(client, flush_every=10 ** 9)
    rclock._pending = 300
    rclock.flush()  # fails: the 300 steps must survive
    assert rclock._pending == 300
    assert client.ticked == []
    rclock.flush()  # heals: everything delivered, nothing double-counted
    assert client.ticked == [300]
    assert rclock._pending == 0


class TestFleetEndToEndChaos:
    @pytest.mark.slow
    @pytest.mark.timeout(900)
    def test_real_fleet_survives_gateway_restart(self, tmp_path):
        """The full acceptance scenario on the REAL stack: thread-backend
        learner + 2 remote actors over localhost, gateway killed and
        rebound mid-run.  Every actor reconnects, re-claims its slot via
        incarnation fencing, resends its unacked chunk, and the run
        completes — no abandoned slots, no duplicate-slot epsilon skew,
        no fake 'run complete'."""
        from pytorch_distributed_tpu.fleet import (
            FleetTopology, _remote_actor_main,
        )

        opt = build_options(
            1, num_actors=2, root_dir=str(tmp_path), seed=7,
            steps=30, learn_start=20, memory_size=512, batch_size=16,
            actor_freq=25, actor_sync_freq=20, param_publish_freq=10,
            learner_freq=10, evaluator_freq=1, evaluator_nepisodes=1,
            checkpoint_freq=0, early_stop=50,
        )
        topo = FleetTopology(opt, local_actors=0, port=0)
        actors = [
            threading.Thread(
                target=_remote_actor_main,
                args=(opt, f"127.0.0.1:{topo.port}", ind), daemon=True)
            for ind in range(2)
        ]
        for t in actors:
            t.start()

        restarted = threading.Event()

        def chaos():
            deadline = time.monotonic() + 600
            while time.monotonic() < deadline:
                if (topo.gateway.chunks_in >= 2
                        and not topo.clock.stop.is_set()):
                    topo.restart_gateway()
                    restarted.set()
                    return
                time.sleep(0.1)

        killer = threading.Thread(target=chaos, daemon=True)
        killer.start()
        topo.run(backend="thread")
        killer.join(10)
        for t in actors:
            t.join(30)
            assert not t.is_alive()
        assert restarted.is_set(), "chaos never fired; scenario not tested"
        assert topo.clock.learner_step.value >= 30  # run COMPLETED
        assert topo.clock.actor_step.value > 0
        # the post-restart gateway carried the rest of the run: both
        # actors re-attached and streamed experience through it
        assert topo.gateway.connections >= 2
        assert topo.gateway.chunks_in > 0
        # clean exits free the slots — asynchronously, on the gateway's
        # serve threads, so poll rather than assert-once
        deadline = time.monotonic() + 10
        while topo.gateway.active_slots:
            assert time.monotonic() < deadline
            time.sleep(0.02)
