"""Evaluator capture/evaluate decoupling (agents/evaluator.py): curve
points must carry cadence-true capture attribution even when the greedy
episodes themselves are starved/slow — the round-3 seed-200 north-star
caveat (evals thinned to ~1/10 min under evaluator_nice, crossing
timestamp became a sampling artifact) made structural."""

import threading
import time

import numpy as np

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.factory import probe_env
from pytorch_distributed_tpu.agents import evaluator as evaluator_mod
from pytorch_distributed_tpu.agents.clocks import EvaluatorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.runtime import _count_params


def test_capture_cadence_survives_slow_evals(tmp_path, monkeypatch):
    FREQ, EVAL_SECS = 0.3, 0.9
    opt = build_options(1, root_dir=str(tmp_path), evaluator_freq=FREQ,
                        evaluator_nepisodes=1, steps=10 ** 9)
    spec = probe_env(opt)
    clock = GlobalClock()
    stats = EvaluatorStats()
    store = ParamStore(_count_params(opt, spec))
    store.publish(np.zeros(store.num_params, np.float32))
    clock.set_learner_step(7)

    # each "eval" takes 3x the capture cadence
    def slow_episodes(opt_, spec_, model, params, env, nepisodes):
        time.sleep(EVAL_SECS)
        return 1.0, 1.0, 1

    monkeypatch.setattr(evaluator_mod, "greedy_episodes", slow_episodes)

    t = threading.Thread(
        target=evaluator_mod.run_evaluator,
        args=(opt, spec, 0, None, store, clock, stats), daemon=True)
    t.start()

    # consume like the logger does, recording capture attribution
    points = []
    publish_walls = []
    deadline = time.monotonic() + 4.0
    while time.monotonic() < deadline:
        got = stats.consume()
        if got is not None:
            points.append(got)
            publish_walls.append(time.monotonic())
        time.sleep(0.02)
    clock.stop.set()
    t.join(timeout=15.0)
    assert not t.is_alive()

    assert len(points) >= 3
    # capture attribution: wall deltas between consecutive points track the
    # CAPTURE cadence (FREQ), not the ~EVAL_SECS publish spacing
    walls = [w for _s, w, _ev in points]
    assert all(w > 0 for w in walls)
    cap_deltas = np.diff(walls)
    pub_deltas = np.diff(publish_walls)
    assert np.median(cap_deltas) < 0.6 * np.median(pub_deltas), (
        cap_deltas, pub_deltas)
    # every point carries the learner step at capture
    assert all(s == 7 for s, _w, _ev in points)


def test_consume_returns_wall_and_resets_flag():
    stats = EvaluatorStats()
    stats.publish(42, wall=123.5, avg_steps=1.0, avg_reward=2.0,
                  nepisodes=1.0, nepisodes_solved=1.0)
    step, wall, ev = stats.consume()
    assert (step, wall) == (42, 123.5)
    assert ev["avg_reward"] == 2.0
    assert stats.consume() is None
