import numpy as np
import pytest

from pytorch_distributed_tpu.ops.nstep import NStepAssembler, nstep_from_episode


def _run_assembler(nstep, gamma, rewards, terminal=True):
    """Feed a synthetic episode; states are scalars 0..T."""
    T = len(rewards)
    asm = NStepAssembler(nstep, gamma)
    out = []
    for t in range(T):
        out.extend(asm.feed(
            state0=np.float32(t), action=np.int32(t % 2),
            reward=float(rewards[t]), state1=np.float32(t + 1),
            terminal=(t == T - 1) and terminal,
            truncated=(t == T - 1) and not terminal))
    return out


@pytest.mark.parametrize("nstep", [1, 3, 5])
@pytest.mark.parametrize("T", [1, 2, 5, 9])
def test_assembler_matches_vectorized(nstep, T):
    gamma = 0.9
    rng = np.random.default_rng(T * 10 + nstep)
    rewards = rng.normal(size=T)
    states = np.arange(T + 1, dtype=np.float32)
    actions = (np.arange(T) % 2).astype(np.int32)

    got = _run_assembler(nstep, gamma, rewards)
    want = nstep_from_episode(states, actions, rewards, nstep, gamma)

    assert len(got) == T
    for t, tr in enumerate(got):
        assert tr.state0 == want.state0[t]
        assert tr.action == want.action[t]
        np.testing.assert_allclose(tr.reward, want.reward[t], rtol=1e-5)
        np.testing.assert_allclose(tr.gamma_n, want.gamma_n[t], rtol=1e-6)
        assert tr.state1 == want.state1[t]
        assert tr.terminal1 == want.terminal1[t]


def test_nstep_reward_sum_by_hand():
    # T=4, nstep=3, gamma=0.5, rewards 1,2,3,4
    out = _run_assembler(3, 0.5, [1, 2, 3, 4])
    # t=0: R = 1 + 0.5*2 + 0.25*3 = 2.75, m=3, s1=3, term=0
    assert out[0].reward == pytest.approx(2.75)
    assert out[0].gamma_n == pytest.approx(0.125)
    assert out[0].state1 == 3.0 and out[0].terminal1 == 0.0
    # t=1: R = 2 + 0.5*3 + 0.25*4 = 4.5, m=3, ends at T -> terminal
    assert out[1].reward == pytest.approx(4.5)
    assert out[1].terminal1 == 1.0
    # t=2 (flush, m=2): R = 3 + 0.5*4 = 5, gamma_n = 0.25
    assert out[2].reward == pytest.approx(5.0)
    assert out[2].gamma_n == pytest.approx(0.25)
    assert out[2].terminal1 == 1.0
    # t=3 (flush, m=1): R = 4
    assert out[3].reward == pytest.approx(4.0)
    assert out[3].gamma_n == pytest.approx(0.5)


def test_truncation_bootstraps():
    out = _run_assembler(3, 0.9, [1, 1, 1, 1], terminal=False)
    assert all(tr.terminal1 == 0.0 for tr in out)
    assert len(out) == 4


def test_emission_timing_steady_state():
    asm = NStepAssembler(3, 0.9)
    emitted = []
    for t in range(6):
        emitted.append(len(asm.feed(t, 0, 1.0, t + 1, terminal=False)))
    # first two feeds emit nothing, then one per feed
    assert emitted == [0, 0, 1, 1, 1, 1]
    assert asm.pending == 2
    assert len(asm.flush()) == 2
    assert asm.pending == 0


def test_single_step_episode():
    out = _run_assembler(5, 0.9, [7.0])
    assert len(out) == 1
    assert out[0].reward == pytest.approx(7.0)
    assert out[0].gamma_n == pytest.approx(0.9)
    assert out[0].terminal1 == 1.0


class _RecordingMemory:
    def __init__(self):
        self.fed = []

    def feed(self, t, priority=None):
        self.fed.append((t, priority))


def test_actor_side_per_priorities():
    """The delayed TD-estimate priorities: steady-state windows resolve
    against the NEXT tick's q_max; terminal windows resolve immediately
    with zero bootstrap; truncated tails take max priority (None)."""
    from pytorch_distributed_tpu.agents.actor import _ActorHarness
    from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import probe_env, build_model, init_params
    from pytorch_distributed_tpu.agents.param_store import make_flattener
    import numpy as np

    opt = build_options(config=1, memory_type="prioritized",
                        num_envs_per_actor=1, nstep=2)
    opt.agent_params.nstep = 2
    opt.agent_params.gamma = 0.5
    spec = probe_env(opt)
    mem = _RecordingMemory()
    # publish the actor's own init so the harness's startup wait() returns
    model = build_model(opt, spec)
    p0 = init_params(opt, spec, model, seed=123)
    flat, _ = make_flattener(p0)
    store = ParamStore(flat.size)
    store.publish(flat)
    clock = GlobalClock()
    h = _ActorHarness(opt, spec, 0, mem, store, clock, ActorStats())
    assert h.per_priorities
    h.start()

    obs = h._obs
    # tick 1: env step (action right, reward 0, no terminal for 8-chain)
    nobs, r, term, infos = h.env.step([1])
    h.advance(np.array([1]), nobs, r, term, infos,
              q_sel=np.array([0.3]), q_max=np.array([9.9]))
    assert mem.fed == []          # nstep=2: no window closed yet
    # tick 2: first window (t=0) closes steady-state -> held for next tick
    nobs, r, term, infos = h.env.step([1])
    h.advance(np.array([1]), nobs, r, term, infos,
              q_sel=np.array([0.7]), q_max=np.array([1.5]))
    assert mem.fed == []          # held: bootstrap q arrives next tick
    # tick 3: pending resolves with THIS tick's q_max=2.0
    nobs, r, term, infos = h.env.step([1])
    h.advance(np.array([1]), nobs, r, term, infos,
              q_sel=np.array([0.1]), q_max=np.array([2.0]))
    assert len(mem.fed) == 1
    t0, pr0 = mem.fed[0]
    # window t=0: R=0 (chain pays only at the end), gamma_m=0.25,
    # q_sel(t0)=0.3 -> |0 + 0.25*2.0 - 0.3| = 0.2
    np.testing.assert_allclose(pr0, abs(0.25 * 2.0 - 0.3), rtol=1e-6)

    # drive to terminal (chain length 8: 4 more rights)
    fed_before = len(mem.fed)
    qs = [0.4, 0.5, 0.6, 0.8]
    for k in range(4):
        nobs, r, term, infos = h.env.step([1])
        h.advance(np.array([1]), nobs, r, term, infos,
                  q_sel=np.array([qs[k]]), q_max=np.array([3.0]))
        if term[0]:
            break
    assert term[0]
    # terminal tick: remaining windows close immediately, priority
    # |R - q_sel(t)| with zero bootstrap; the last window's R is the
    # terminal reward 1.0 discounted appropriately
    terminal_feeds = mem.fed[fed_before:]
    assert len(terminal_feeds) >= 2
    for t, pr in terminal_feeds:
        assert pr is not None
        if float(t.terminal1) == 1.0:
            assert pr >= 0.0
    # q history drained clean at the boundary
    assert not h._q_hist[0]
    assert not h._q_pending[0]
