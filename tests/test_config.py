import pytest

from pytorch_distributed_tpu.config import CONFIGS, build_agent_params, build_options


def test_configs_table_shape():
    for row in CONFIGS:
        assert len(row) == 5


def test_reference_dqn_defaults():
    # mirror reference utils/options.py:112-141
    p = build_agent_params("dqn")
    assert p.steps == 500000
    assert p.gamma == 0.99
    assert p.lr == 1e-4
    assert p.batch_size == 128
    assert p.learn_start == 5000
    assert p.target_model_update == 250
    assert p.nstep == 5
    assert p.eps == 0.4 and p.eps_alpha == 7
    assert p.actor_sync_freq == 100
    assert p.enable_double is False


def test_reference_ddpg_defaults():
    # mirror reference utils/options.py:142-168
    p = build_agent_params("ddpg")
    assert p.batch_size == 64
    assert p.clip_grad == 40.0
    assert p.target_model_update == 1e-3
    assert p.learn_start == 250
    assert p.actor_sync_freq == 400


def test_build_options_routes_overrides():
    opt = build_options(config=1, num_actors=2, batch_size=32, memory_size=100)
    assert opt.num_actors == 2
    assert opt.agent_params.batch_size == 32
    assert opt.memory_params.memory_size == 100
    assert opt.agent_type == "dqn"
    with pytest.raises(ValueError):
        build_options(config=1, not_a_key=3)


def test_cnn_config_shapes():
    opt = build_options(config=0)
    assert opt.env_params.state_shape == (4, 84, 84)
    assert opt.memory_params.state_dtype == "uint8"


def test_per_config():
    opt = build_options(config=6)
    assert opt.memory_params.enable_per is True


def test_test_mode_defaults_model_file():
    opt = build_options(config=1, mode=2)
    assert opt.model_file == opt.model_name


def test_selector_overrides_recompute_defaults():
    # agent_type override must pull DDPG hyperparameter defaults
    o = build_options(config=1, agent_type="ddpg")
    assert o.agent_params.batch_size == 64
    assert o.agent_params.clip_grad == 40.0
    # memory_type override must flip the PER flag
    assert build_options(config=0, memory_type="prioritized").memory_params.enable_per
    # model_type override must re-derive the state dtype family
    assert build_options(config=0, model_type="dqn-mlp").memory_params.state_dtype == "float32"


def test_parse_set_overrides_types():
    from pytorch_distributed_tpu.config import parse_set_overrides

    out = parse_set_overrides([
        "steps=2000", "lr=2e-3", "game=pong", "value_rescale=false",
        "enable_double=True",
    ])
    assert out["steps"] == 2000 and isinstance(out["steps"], int)
    assert out["lr"] == 2e-3
    assert out["game"] == "pong"
    assert out["value_rescale"] is False
    assert out["enable_double"] is True
