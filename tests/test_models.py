import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models import (
    DdpgMlpModel, DqnCnnModel, DqnMlpModel,
    apex_epsilon, build_ddpg_act, build_epsilon_greedy_act,
)
from pytorch_distributed_tpu.models.policies import build_greedy_act


def test_dqn_cnn_shapes_and_dtype():
    model = DqnCnnModel(action_space=6)
    x = DqnCnnModel.example_input(batch=2)
    params = model.init(jax.random.PRNGKey(0), x)
    q = model.apply(params, x)
    assert q.shape == (2, 6)
    assert q.dtype == jnp.float32


def test_dqn_cnn_conv_trunk_size():
    # Nature trunk on 84x84 flattens to 7*7*64 = 3136 before the 512 dense
    model = DqnCnnModel(action_space=4)
    params = model.init(jax.random.PRNGKey(0), DqnCnnModel.example_input())
    dense_kernel = params["params"]["Dense_0"]["kernel"]
    assert dense_kernel.shape == (3136, 512)


def test_dqn_cnn_normalisation():
    # all-zero and all-255 inputs must produce different Q values, and the
    # input is normalised so activations stay sane
    model = DqnCnnModel(action_space=4)
    x0 = jnp.zeros((1, 4, 84, 84), dtype=jnp.uint8)
    x1 = jnp.full((1, 4, 84, 84), 255, dtype=jnp.uint8)
    params = model.init(jax.random.PRNGKey(0), x0)
    q0, q1 = model.apply(params, x0), model.apply(params, x1)
    assert not np.allclose(q0, q1)
    assert np.all(np.abs(q1) < 100)


def test_dqn_mlp_shapes():
    model = DqnMlpModel(action_space=2)
    x = jnp.zeros((5, 8), dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    q = model.apply(params, x)
    assert q.shape == (5, 2)


def test_ddpg_model_paths():
    model = DdpgMlpModel(action_dim=1)
    x = jnp.zeros((3, 3), dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    a, q = model.apply(params, x)
    assert a.shape == (3, 1) and q.shape == (3,)
    assert np.all(np.abs(a) <= 1.0)
    a2 = model.apply(params, x, method=model.forward_actor)
    np.testing.assert_allclose(a, a2)
    q2 = model.apply(params, x, a2, method=model.forward_critic)
    np.testing.assert_allclose(q, q2, rtol=1e-6)


def test_ddpg_out_init_small():
    model = DdpgMlpModel(action_dim=2)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 5)))
    out_k = params["params"]["actor_out"]["kernel"]
    assert np.max(np.abs(out_k)) <= 3e-3


def test_apex_epsilon_schedule():
    # reference dqn_actor.py:33-36: actor 0 gets eps**1, last actor eps**8
    assert apex_epsilon(0, 8) == pytest.approx(0.4)
    assert apex_epsilon(7, 8) == pytest.approx(0.4 ** 8)
    assert apex_epsilon(0, 1) == 0.1
    eps = [apex_epsilon(i, 8) for i in range(8)]
    assert eps == sorted(eps, reverse=True)


def test_epsilon_greedy_act():
    model = DqnMlpModel(action_space=3)
    x = jnp.zeros((4, 6))
    params = model.init(jax.random.PRNGKey(0), x)
    act = build_epsilon_greedy_act(model.apply)
    a, q_sel, q_max = act(params, x, jax.random.PRNGKey(1), 0.0)
    assert a.shape == (4,)
    # greedy: selected q == max q
    np.testing.assert_allclose(q_sel, q_max)
    # eps=1: all random; over many keys all actions appear
    actions = set()
    for i in range(20):
        a, _, _ = act(params, x, jax.random.PRNGKey(i), 1.0)
        actions.update(np.asarray(a).tolist())
    assert actions == {0, 1, 2}


def test_greedy_act():
    model = DqnMlpModel(action_space=3)
    x = jnp.ones((2, 6))
    params = model.init(jax.random.PRNGKey(0), x)
    act = build_greedy_act(model.apply)
    a, qm = act(params, x)
    q = model.apply(params, x)
    np.testing.assert_array_equal(a, np.argmax(q, axis=-1))


def test_ddpg_act():
    model = DdpgMlpModel(action_dim=2)
    x = jnp.zeros((3, 4))
    params = model.init(jax.random.PRNGKey(0), x)
    act = build_ddpg_act(
        lambda p, o: model.apply(p, o, method=model.forward_actor))
    a = act(params, x)
    assert a.shape == (3, 2)
