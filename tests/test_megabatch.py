"""ISSUE-13 megabatch oracles: the fused megabatched learner step must
reproduce an unfused reference of its documented semantics exactly —
params, optimizer state, PER priorities and the key-stream schedule —
for both flat families (dqn, decoupled ddpg), with M=1 degenerating to
the production sequential step.  Plus the perf-plane drills the other
fused dispatches carry: no post-warmup retrace, transfer-audit-clean.

Group semantics under test (config.LearnerPerfParams docstring): all M
minibatch gradients at the GROUP-ENTRY params in one batched backward,
optimizer updates applied sequentially, PER write-backs in minibatch
order from group-entry sampling distributions.  Tolerances are a few
fp32 ulps (vmapped and unbatched backwards order their reductions
identically on this backend, but XLA does not contract to bitwise)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
import optax

from pytorch_distributed_tpu.models import DdpgMlpModel, DqnMlpModel
from pytorch_distributed_tpu.ops.losses import (
    build_ddpg_megabatch_step, build_ddpg_train_step,
    build_dqn_megabatch_step, build_dqn_train_step, init_ddpg_train_state,
    init_train_state, make_optimizer, merge_ddpg_params,
)
from pytorch_distributed_tpu.utils.experience import Batch, Transition
from pytorch_distributed_tpu.utils.health import SKIPPED_KEY

OBS, ACT, B = 4, 3, 8

TOL = dict(rtol=2e-6, atol=2e-6)


def _dqn_setup(lr=1e-2, guard=True, target_update=3):
    model = DqnMlpModel(action_space=ACT, hidden_dim=32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, OBS)))
    tx = make_optimizer(lr)
    state = init_train_state(params, tx)
    mega = build_dqn_megabatch_step(model.apply, tx, guard=guard,
                                    target_model_update=target_update)
    return model, tx, state, mega


def _batches(M, seed=0):
    """A (M, B)-leading Batch group."""
    rng = np.random.default_rng(seed)
    return Batch(
        state0=rng.normal(size=(M, B, OBS)).astype(np.float32),
        action=rng.integers(0, ACT, size=(M, B)).astype(np.int32),
        reward=rng.normal(size=(M, B)).astype(np.float32),
        gamma_n=np.full((M, B), 0.95, dtype=np.float32),
        state1=rng.normal(size=(M, B, OBS)).astype(np.float32),
        terminal1=(rng.random((M, B)) < 0.3).astype(np.float32),
        weight=np.ones((M, B), np.float32),
        index=np.tile(np.arange(B, dtype=np.int32), (M, 1)),
    )


def _mb(batches, i):
    return jax.tree_util.tree_map(lambda l: l[i], batches)


def _assert_tree_close(a, b, **kw):
    kw = kw or TOL
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_allclose(np.asarray(x),
                                                np.asarray(y), **kw),
        a, b)


class TestDqnMegabatchOracle:
    def test_matches_unfused_sequential_reference(self):
        """The fused group step == a python loop implementing the
        documented semantics with the production optimizer pieces."""
        model, tx, state, mega = _dqn_setup()
        M = 4
        batches = _batches(M)
        new_state, metrics, td_abs, ok = jax.jit(mega)(state, batches)
        assert np.asarray(ok).tolist() == [1.0] * M
        assert float(metrics[SKIPPED_KEY]) == 0.0

        def loss_fn(p, tgt, b):
            q = model.apply(p, b.state0)
            q_sel = jnp.take_along_axis(
                q, b.action.astype(jnp.int32).reshape(-1, 1), axis=1)[:, 0]
            boot = jnp.max(model.apply(tgt, b.state1), axis=-1)
            t = b.reward + b.gamma_n * boot * (1.0 - b.terminal1)
            return jnp.mean(b.weight * jnp.square(
                q_sel - jax.lax.stop_gradient(t)))

        from pytorch_distributed_tpu.utils.helpers import update_target

        p, o, s, t = (state.params, state.opt_state, state.step,
                      state.target_params)
        entry_p, entry_t = state.params, state.target_params
        ref_tds = []
        for i in range(M):
            b = _mb(batches, i)
            g = jax.grad(loss_fn)(entry_p, entry_t, b)
            upd, o = tx.update(g, o, p)
            p = optax.apply_updates(p, upd)
            s = s + 1
            t = update_target(t, p, s, 3)
        _assert_tree_close(new_state.params, p)
        _assert_tree_close(new_state.target_params, t)
        _assert_tree_close(new_state.opt_state, o)
        assert int(new_state.step) == int(s) == M

    def test_m1_group_equals_production_sequential_step(self):
        """With M=1 the group semantics ARE the sequential step's: same
        params, target, opt state, metrics, td."""
        model, tx, state, mega = _dqn_setup()
        seq = build_dqn_train_step(model.apply, tx, target_model_update=3)
        batches = _batches(1)
        s_m, m_m, td_m, ok = jax.jit(mega)(state, batches)
        s_s, m_s, td_s = jax.jit(seq)(state, _mb(batches, 0))
        _assert_tree_close(s_m.params, s_s.params)
        _assert_tree_close(s_m.opt_state, s_s.opt_state)
        np.testing.assert_allclose(np.asarray(td_m[0]), np.asarray(td_s),
                                   **TOL)
        for k in ("learner/critic_loss", "learner/q_mean",
                  "learner/grad_norm"):
            np.testing.assert_allclose(float(m_m[k]), float(m_s[k]),
                                       **TOL)

    def test_guard_skips_only_the_poisoned_minibatch(self):
        model, tx, state, mega = _dqn_setup()
        M = 3
        batches = _batches(M)
        reward = np.asarray(batches.reward).copy()
        reward[1] = np.nan  # poison the MIDDLE minibatch only
        batches = batches._replace(reward=reward)
        new_state, metrics, td_abs, ok = jax.jit(mega)(state, batches)
        assert np.asarray(ok).tolist() == [1.0, 0.0, 1.0]
        assert float(metrics[SKIPPED_KEY]) == 1.0
        # the skipped row's TD is zeroed so no write-back path can
        # scatter NaN priorities
        assert np.all(np.asarray(td_abs[1]) == 0.0)
        assert np.isfinite(
            np.asarray(ravel_pytree(new_state.params)[0])).all()
        # skipped minibatch does not advance the step counter
        assert int(new_state.step) == M - 1
        # and the applied updates equal the reference that drops mb 1
        ref_state, _m, _td, _ok = jax.jit(mega)(
            state, jax.tree_util.tree_map(
                lambda l: l[np.array([0, 2])], batches))
        _assert_tree_close(new_state.params, ref_state.params)

    def test_all_poisoned_group_passes_state_through(self):
        model, tx, state, mega = _dqn_setup()
        batches = _batches(2)
        batches = batches._replace(
            reward=np.full_like(np.asarray(batches.reward), np.nan))
        new_state, metrics, _td, ok = jax.jit(mega)(state, batches)
        assert float(metrics[SKIPPED_KEY]) == 2.0
        _assert_tree_close(new_state.params, state.params,
                           rtol=0.0, atol=0.0)
        assert int(new_state.step) == 0


class TestDdpgMegabatchOracle:
    def _setup(self):
        model = DdpgMlpModel(action_dim=1, norm_val=1.0)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, OBS)))
        atx = make_optimizer(1e-2)
        ctx_ = make_optimizer(1e-2)
        state = init_ddpg_train_state(params, atx, ctx_)
        actor_apply = lambda p, o: model.apply(p, o,
                                               method=model.forward_actor)
        critic_apply = lambda p, o, a: model.apply(
            p, o, a, method=model.forward_critic)
        mega = build_ddpg_megabatch_step(actor_apply, critic_apply,
                                         atx, ctx_,
                                         target_model_update=1e-3)
        return model, atx, ctx_, state, actor_apply, critic_apply, mega

    def _cont_batches(self, M, seed=0):
        rng = np.random.default_rng(seed)
        return Batch(
            state0=rng.normal(size=(M, B, OBS)).astype(np.float32),
            action=rng.uniform(-1, 1, size=(M, B, 1)).astype(np.float32),
            reward=rng.normal(size=(M, B)).astype(np.float32),
            gamma_n=np.full((M, B), 0.95, dtype=np.float32),
            state1=rng.normal(size=(M, B, OBS)).astype(np.float32),
            terminal1=(rng.random((M, B)) < 0.3).astype(np.float32),
            weight=np.ones((M, B), np.float32),
            index=np.tile(np.arange(B, dtype=np.int32), (M, 1)),
        )

    def test_matches_unfused_sequential_reference(self):
        (model, atx, ctx_, state, actor_apply, critic_apply,
         mega) = self._setup()
        M = 3
        batches = self._cont_batches(M)
        new_state, metrics, td_abs, ok = jax.jit(mega)(state, batches)
        assert np.asarray(ok).tolist() == [1.0] * M

        from pytorch_distributed_tpu.utils.helpers import update_target

        # ddpg tolerance is looser than dqn's: the two-net backward's
        # vmapped reductions differ from the unbatched ones by ~1 ulp,
        # and Adam's m/sqrt(v) amplifies that to ~1e-5 on a handful of
        # elements (a SEMANTIC divergence — wrong critic, wrong order —
        # would shift lr-scale ~1e-3 across the tree)
        ddpg_tol = dict(rtol=1e-4, atol=5e-5)

        params, target = state.params, state.target_params
        target_full = merge_ddpg_params(target["actor"],
                                        target["critic"])

        def critic_loss(cp, ap_, b):
            full = merge_ddpg_params(ap_, cp)
            q = critic_apply(full, b.state0, b.action)
            a_next = actor_apply(target_full, b.state1)
            q_next = critic_apply(target_full, b.state1, a_next)
            tgt = b.reward + b.gamma_n * q_next * (1.0 - b.terminal1)
            return jnp.mean(b.weight * jnp.square(
                q - jax.lax.stop_gradient(tgt)))

        def actor_loss(ap_, cp, b):
            full = merge_ddpg_params(ap_, cp)
            a = actor_apply(full, b.state0)
            return -jnp.mean(critic_apply(full, b.state0, a))

        # stage 1: critic grads at entry; sequential critic chain
        cp, copt = params["critic"], state.opt_state["critic"]
        critics = []
        for i in range(M):
            g = jax.grad(critic_loss)(params["critic"], params["actor"],
                                      _mb(batches, i))
            upd, copt = ctx_.update(g, copt, cp)
            cp = optax.apply_updates(cp, upd)
            critics.append(cp)
        # stage 2: actor grads at (entry actor, FINAL critic)
        ap_, aopt = params["actor"], state.opt_state["actor"]
        tgt, s = target, state.step
        for i in range(M):
            g = jax.grad(actor_loss)(params["actor"], cp, _mb(batches, i))
            upd, aopt = atx.update(g, aopt, ap_)
            ap_ = optax.apply_updates(ap_, upd)
            s = s + 1
            tgt = update_target(tgt, {"actor": ap_, "critic": critics[i]},
                                s, 1e-3)
        _assert_tree_close(new_state.params["critic"], cp, **ddpg_tol)
        _assert_tree_close(new_state.params["actor"], ap_, **ddpg_tol)
        _assert_tree_close(new_state.target_params, tgt, **ddpg_tol)
        assert int(new_state.step) == M

    def test_m1_group_equals_production_sequential_step(self):
        (model, atx, ctx_, state, actor_apply, critic_apply,
         mega) = self._setup()
        seq = build_ddpg_train_step(actor_apply, critic_apply, atx, ctx_,
                                    target_model_update=1e-3)
        batches = self._cont_batches(1)
        s_m, m_m, td_m, _ok = jax.jit(mega)(state, batches)
        s_s, m_s, td_s = jax.jit(seq)(state, _mb(batches, 0))
        _assert_tree_close(s_m.params, s_s.params)
        _assert_tree_close(s_m.target_params, s_s.target_params)
        np.testing.assert_allclose(np.asarray(td_m[0]), np.asarray(td_s),
                                   **TOL)
        for k in ("learner/critic_loss", "learner/actor_loss",
                  "learner/grad_norm"):
            np.testing.assert_allclose(float(m_m[k]), float(m_s[k]),
                                       **TOL)


# ---------------------------------------------------------------------------
# fused-dispatch oracles over real rings (the learner's actual programs)
# ---------------------------------------------------------------------------

def _fill_ring(ring, n=128, seed=0, num_actions=ACT):
    rng = np.random.default_rng(seed)
    ring.feed_chunk(Transition(
        state0=rng.normal(size=(n, OBS)).astype(np.float32),
        action=rng.integers(0, num_actions, n).astype(np.int32),
        reward=rng.normal(size=n).astype(np.float32),
        gamma_n=np.full(n, 0.95, np.float32),
        state1=rng.normal(size=(n, OBS)).astype(np.float32),
        terminal1=(rng.random(n) < 0.2).astype(np.float32)))


class TestFusedMegabatchDispatch:
    def test_uniform_key_schedule_and_reference_parity(self):
        """One megabatched dispatch over the uniform HBM ring consumes
        keys exactly as the sequential schedule (key g*M+i draws group
        g's minibatch i) and lands on the unfused reference."""
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplay, build_uniform_fused_step, sample_rows,
        )
        from pytorch_distributed_tpu.utils.helpers import update_target

        model, tx, state, mega = _dqn_setup()
        seq_step = build_dqn_train_step(model.apply, tx,
                                        target_model_update=3)
        ring = DeviceReplay(128, (OBS,), state_dtype=np.float32)
        _fill_ring(ring)
        M, K = 2, 4
        fused = build_uniform_fused_step(seq_step, B, steps_per_call=K,
                                         donate=False, megabatch=M,
                                         megabatch_step=mega)
        keys = jax.random.split(jax.random.PRNGKey(7), K)
        new_state, metrics = fused(state, ring.state, keys)

        def loss_fn(p, tgt, b):
            q = model.apply(p, b.state0)
            q_sel = jnp.take_along_axis(
                q, b.action.astype(jnp.int32).reshape(-1, 1),
                axis=1)[:, 0]
            boot = jnp.max(model.apply(tgt, b.state1), axis=-1)
            t = b.reward + b.gamma_n * boot * (1.0 - b.terminal1)
            return jnp.mean(b.weight * jnp.square(
                q_sel - jax.lax.stop_gradient(t)))

        p, o, s, t = (state.params, state.opt_state, state.step,
                      state.target_params)
        for g0 in range(K // M):
            entry_p, entry_t = p, t
            for i in range(M):
                # the key-stream schedule contract: minibatch i of
                # group g samples with key g*M+i — the same draw the
                # sequential scan would make
                b = sample_rows(ring.state, keys[g0 * M + i], B)
                g = jax.grad(loss_fn)(entry_p, entry_t, b)
                upd, o = tx.update(g, o, p)
                p = optax.apply_updates(p, upd)
                s = s + 1
                t = update_target(t, p, s, 3)
        _assert_tree_close(new_state.params, p)
        assert float(metrics[SKIPPED_KEY]) == 0.0

    def test_per_dispatch_matches_unfused_reference(self):
        """The PER megabatched dispatch: group-entry sampling, grads at
        group entry, write-backs in minibatch order — priorities AND
        params land on the unfused reference."""
        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay, per_sample, per_update_priorities,
        )
        from pytorch_distributed_tpu.utils.helpers import update_target

        model, tx, state, mega = _dqn_setup()
        seq_step = build_dqn_train_step(model.apply, tx,
                                        target_model_update=3)
        per = DevicePerReplay(128, (OBS,), state_dtype=np.float32)
        _fill_ring(per)
        M, K = 2, 4
        fused = per.build_fused_step(seq_step, B, donate=False,
                                     steps_per_call=K, megabatch=M,
                                     megabatch_step=mega)
        keys = jax.random.split(jax.random.PRNGKey(5), K)
        beta = jnp.float32(0.5)
        new_state, rs, metrics = fused(state, per.state, keys, beta)

        def loss_fn(p, tgt, b):
            q = model.apply(p, b.state0)
            q_sel = jnp.take_along_axis(
                q, b.action.astype(jnp.int32).reshape(-1, 1),
                axis=1)[:, 0]
            boot = jnp.max(model.apply(tgt, b.state1), axis=-1)
            t = b.reward + b.gamma_n * boot * (1.0 - b.terminal1)
            td = q_sel - jax.lax.stop_gradient(t)
            return jnp.mean(b.weight * jnp.square(td)), jnp.abs(td)

        p, o, s, t = (state.params, state.opt_state, state.step,
                      state.target_params)
        rs_ref = per.state
        for g0 in range(K // M):
            entry_p, entry_t, entry_rs = p, t, rs_ref
            drawn, tds = [], []
            for i in range(M):
                b = per_sample(entry_rs, keys[g0 * M + i], B, beta)
                (_l, td), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(entry_p, entry_t, b)
                upd, o = tx.update(g, o, p)
                p = optax.apply_updates(p, upd)
                s = s + 1
                t = update_target(t, p, s, 3)
                drawn.append(b)
                tds.append(td)
            for i in range(M):
                rs_ref = per_update_priorities(rs_ref, drawn[i].index,
                                               tds[i], per.alpha)
        _assert_tree_close(new_state.params, p)
        np.testing.assert_allclose(np.asarray(rs.priority),
                                   np.asarray(rs_ref.priority), **TOL)

    def test_per_poisoned_group_leaves_priorities_untouched(self):
        """All-NaN rewards: every minibatch skipped, params pass
        through, and the write-back suppression keeps every priority
        leaf exactly as it was."""
        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay,
        )

        model, tx, state, mega = _dqn_setup()
        seq_step = build_dqn_train_step(model.apply, tx,
                                        target_model_update=3)
        per = DevicePerReplay(128, (OBS,), state_dtype=np.float32)
        _fill_ring(per)
        per.state = per.state._replace(
            reward=jnp.full_like(per.state.reward, jnp.nan))
        prio_before = np.asarray(per.state.priority).copy()
        M, K = 2, 2
        fused = per.build_fused_step(seq_step, B, donate=False,
                                     steps_per_call=K, megabatch=M,
                                     megabatch_step=mega)
        keys = jax.random.split(jax.random.PRNGKey(1), K)
        new_state, rs, metrics = fused(state, per.state, keys,
                                       jnp.float32(0.5))
        assert float(metrics[SKIPPED_KEY]) == K
        _assert_tree_close(new_state.params, state.params,
                           rtol=0.0, atol=0.0)
        np.testing.assert_array_equal(np.asarray(rs.priority),
                                      prio_before)


class TestMegabatchPerfDrills:
    """The drills every fused hot-path dispatch carries (test_perf.py
    style): the megabatched program must never recompile after warmup
    and must stage zero implicit host transfers."""

    def _fused(self, M=2, K=4):
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplay, build_uniform_fused_step,
        )

        model, tx, state, mega = _dqn_setup()
        seq_step = build_dqn_train_step(model.apply, tx,
                                        target_model_update=3)
        ring = DeviceReplay(128, (OBS,), state_dtype=np.float32)
        _fill_ring(ring)
        fused = build_uniform_fused_step(seq_step, B, steps_per_call=K,
                                         donate=False, megabatch=M,
                                         megabatch_step=mega)
        return fused, state, ring, K

    def test_no_retrace_after_warmup(self):
        from pytorch_distributed_tpu.utils import perf

        fused, state, ring, K = self._fused()
        det = perf.RetraceDetector()
        det.register("mega_fused", getattr(fused, "_cache_size", None))
        key = jax.random.PRNGKey(0)
        for _ in range(3):
            key, sub = jax.random.split(key)
            state, _m = fused(state, ring.state,
                              jax.random.split(sub, K))
        det.check()  # warmup mark
        for _ in range(3):
            key, sub = jax.random.split(key)
            state, _m = fused(state, ring.state,
                              jax.random.split(sub, K))
        assert det.check() == []
        assert det.retraces == 0

    def test_transfer_audit_clean(self):
        from pytorch_distributed_tpu.utils import perf

        fused, state, ring, K = self._fused()
        state = jax.device_put(state)
        rs = jax.device_put(ring.state)
        keys = jax.device_put(
            jax.random.split(jax.random.PRNGKey(0), K))
        aud = perf.TransferAudit()
        state, _m = aud.run(fused, state, rs, keys)
        assert aud.total == 0 and aud.sites == {}


class TestResolveAndFactory:
    def test_resolve_megabatch_rounds_dispatch_up(self):
        from pytorch_distributed_tpu.config import build_options
        from pytorch_distributed_tpu.factory import resolve_megabatch

        opt = build_options(1, megabatch=8)
        assert resolve_megabatch(opt, 1) == (8, 8)
        assert resolve_megabatch(opt, 12) == (8, 16)
        assert resolve_megabatch(opt, 16) == (8, 16)
        opt1 = build_options(1)
        assert resolve_megabatch(opt1, 5) == (1, 5)

    def test_env_override_wins(self, monkeypatch):
        from pytorch_distributed_tpu.utils.perf import resolve_mxu

        monkeypatch.setenv("TPU_APEX_MXU_MEGABATCH", "16")
        lp = resolve_mxu(None)
        assert lp.megabatch == 16
        monkeypatch.setenv("TPU_APEX_MXU_PALLAS_TORSO", "1")
        assert resolve_mxu(None).pallas_torso is True

    def test_unsupported_family_returns_none(self):
        from pytorch_distributed_tpu.config import build_options
        from pytorch_distributed_tpu.factory import (
            build_megabatch_train_step, build_model, probe_env,
        )

        opt = build_options(13)  # r2d2 sequence family
        spec = probe_env(opt)
        model = build_model(opt, spec)
        assert build_megabatch_train_step(opt, model) is None
