"""Unified incident timeline (ISSUE 8): artifact merge + clock
alignment + --around filtering + Perfetto export schema + the CLI
smoke, driven by a REAL seeded poison drill through the production
components (fault injector -> feeder -> quarantine -> blackbox dump)
rather than synthetic fixtures."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.memory.feeder import QueueOwner
from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.utils import flight_recorder, health, tracing
from pytorch_distributed_tpu.utils.experience import Transition, make_prov
from pytorch_distributed_tpu.utils.metrics import MetricsWriter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import timeline  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("FEEDER_FAULTS", "ACTOR_FAULTS", "LEARNER_FAULTS"):
        monkeypatch.delenv(var, raising=False)
    flight_recorder.reset()
    health.reset()
    tracing.reset()
    yield
    flight_recorder.reset()
    health.reset()
    tracing.reset()


def _mk_transition(v: float, prov=None) -> Transition:
    return Transition(
        state0=np.full((4,), v, np.float32), action=np.int32(0),
        reward=np.float32(v), gamma_n=np.float32(0.99),
        state1=np.full((4,), v + 1, np.float32),
        terminal1=np.float32(0.0), prov=prov)


@pytest.fixture()
def drill_dir(tmp_path, monkeypatch):
    """A seeded poison drill through the REAL components: the feeder
    fault plane poisons chunk #1, the QueueOwner ingest boundary
    quarantines it, the sentinel records the anomaly, and the run's
    rings dump — exactly the artifact set a production incident leaves,
    plus metrics rows and a remote role's clock_sync events."""
    log_dir = str(tmp_path)
    monkeypatch.setenv("FEEDER_FAULTS", "poison_chunk@0")
    flight_recorder.configure(log_dir, run_id="drillrun")
    owner = QueueOwner(PrioritizedReplay(capacity=32, state_shape=(4,),
                                         state_dtype=np.float32))
    feeder = owner.make_feeder(chunk=4)
    for i in range(4):
        feeder.feed(_mk_transition(i, make_prov(0, i, 1, i)), 0.5)
    feeder.flush()  # fault plane poisons THIS chunk (frame 0)
    # the spawn-context queue delivers through a feeder thread: drain
    # until the poisoned chunk lands in quarantine (bounded)
    deadline = time.time() + 10.0
    while (health.get_quarantine("feeder-local").count < 4
           and time.time() < deadline):
        owner.drain()
        time.sleep(0.02)
    assert health.get_quarantine("feeder-local").count == 4
    # learner-side incident records + recovery marker
    rec = flight_recorder.get_recorder("learner")
    rec.record("anomaly", step=100, kinds=["skipped"], streak=1)
    rec.record("rollback", epoch=3, step=90, reason="poison drill")
    rec.record("recovered", step=90)
    # a remote actor's ring with a clock offset (its host clock runs
    # 2.5 s BEHIND the learner host's)
    actor_rec = flight_recorder.get_recorder("actor-0")
    actor_rec.record("clock_sync", offset=2.5, slot=0)
    actor_rec.record("episode", reward=1.0)
    # hand-stamp a dcn-client ring too (the role that records offsets
    # in production)
    cli = flight_recorder.get_recorder("dcn-client-0")
    cli.record("clock_sync", offset=2.5, slot=0)
    flight_recorder.dump_all("drill complete")
    # metrics rows: a health scalar, a span, a priority X-ray row
    w = MetricsWriter(log_dir, enable_tensorboard=False, role="learner",
                      run_id="drillrun")
    w.scalar("health/skipped_steps", 1.0, step=100)
    w.span("learn", role="learner", trace_id="00ab", dur_ms=12.5,
           step=100)
    w.bucket_histogram("replay/priority", [5, 3, 0, 1], log10_lo=-6.0,
                       log10_hi=3.0, step=100,
                       extra={"ess": 6.4, "ess_frac": 0.71, "mass": 4.5,
                              "rows": 9})
    w.close()
    return log_dir


class TestBuildTimeline:
    def test_merges_all_planes_ordered(self, drill_dir):
        events = timeline.build_timeline(drill_dir)
        sources = {e["source"] for e in events}
        assert {"blackbox", "scalars", "span", "quarantine"} <= sources
        walls = [e["wall"] for e in events]
        assert walls == sorted(walls)
        kinds = {e["kind"] for e in events}
        # the drill's skeleton: injected fault, quarantine, rollback,
        # recovery, the priority X-ray and the health scalar
        assert {"fault", "quarantine", "rollback", "recovered",
                "priority_xray", "scalar", "span"} <= kinds
        q = next(e for e in events if e["kind"] == "quarantine")
        assert q["run_id"] == "drillrun"
        assert "actor(s) [0]" in q["detail"]
        dump = next(e for e in events if e["kind"] == "blackbox_dump")
        assert dump["run_id"] == "drillrun"

    def test_clock_offset_applied_to_remote_roles(self, drill_dir):
        events = timeline.build_timeline(drill_dir)
        actor_ev = [e for e in events if e["role"] == "actor-0"]
        assert actor_ev
        for e in actor_ev:
            assert e["clock_offset"] == pytest.approx(2.5)
            assert e["wall"] == pytest.approx(e["raw_wall"] + 2.5)
        learner_ev = [e for e in events if e["role"] == "learner"
                      and e["source"] == "blackbox"]
        assert all(e["clock_offset"] == 0.0 for e in learner_ev)

    def test_fault_precedes_quarantine_precedes_recovery(self, drill_dir):
        """The causal chain the tool exists to reconstruct: injected
        poison -> quarantine divert -> anomaly -> rollback ->
        recovery, in clock order across roles."""
        events = timeline.build_timeline(drill_dir)
        order = [e["kind"] for e in events
                 if e["kind"] in ("fault", "quarantine", "anomaly",
                                  "rollback", "recovered")]
        assert order.index("fault") < order.index("quarantine")
        assert order.index("anomaly") < order.index("rollback")
        assert "recovered" in order

    def test_around_window_filters(self, drill_dir):
        events = timeline.build_timeline(drill_dir)
        cut = timeline.filter_around(events, "poison", window=60.0)
        assert cut
        assert any(e.get("anchor") for e in cut)
        assert any(e["kind"] == "quarantine" for e in cut)
        # a zero-width window keeps (near) only the anchor's instant
        tight = timeline.filter_around(events, "quarantine",
                                       window=0.0)
        assert tight and all(
            abs(e["wall"] - next(x["wall"] for x in tight
                                 if x.get("anchor"))) <= 0.0
            for e in tight)
        assert timeline.filter_around(events, "no-such-event", 10) == []

    def test_render_text_marks_incident_lines(self, drill_dir):
        events = timeline.build_timeline(drill_dir)
        text = timeline.render_text(events)
        assert "quarantine" in text
        assert "!!" in text  # loud incident marker


class TestPerfettoExport:
    def test_trace_event_schema(self, drill_dir):
        events = timeline.build_timeline(drill_dir)
        doc = timeline.to_perfetto(events)
        assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
        assert doc["displayTimeUnit"] == "ms"
        json.dumps(doc)  # must be plain-JSON serializable
        phases = set()
        for ev in doc["traceEvents"]:
            assert isinstance(ev["name"], str) and ev["name"]
            assert ev["ph"] in ("M", "i", "X", "C")
            assert isinstance(ev["pid"], int)
            if ev["ph"] != "M":
                assert ev["ts"] >= 0
            if ev["ph"] == "X":
                assert ev["dur"] >= 0
            if ev["ph"] == "i":
                assert ev["s"] in ("g", "p", "t")
            phases.add(ev["ph"])
        assert {"M", "i", "X", "C"} <= phases  # all mappings exercised
        # every role got a named process
        names = {ev["args"]["name"] for ev in doc["traceEvents"]
                 if ev["ph"] == "M"}
        assert "learner" in names and "actor-0" in names

    def test_cli_perfetto_writes_valid_json(self, drill_dir, tmp_path):
        out = str(tmp_path / "trace.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "timeline.py"),
             drill_dir, "--perfetto", out],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        with open(out) as f:
            doc = json.load(f)
        assert doc["traceEvents"]


class TestCli:
    def test_json_smoke(self, drill_dir):
        """Tier-1 CLI smoke (ISSUE 8 satellite): --json emits a parseable
        ordered event list; --around narrows it."""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "timeline.py"),
             drill_dir, "--json"],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr
        events = json.loads(proc.stdout)
        assert len(events) >= 8
        assert all("wall" in e and "role" in e and "kind" in e
                   for e in events)
        proc2 = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "timeline.py"),
             drill_dir, "--around", "rollback", "--window", "120",
             "--json"],
            capture_output=True, text=True, timeout=120, env=env)
        assert proc2.returncode == 0, proc2.stderr
        cut = json.loads(proc2.stdout)
        assert any(e["kind"] == "rollback" for e in cut)

    def test_missing_dir_exits_2(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "timeline.py"),
             "/no/such/dir", "--json"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 2

    def test_no_match_exits_1(self, drill_dir):
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "timeline.py"),
             drill_dir, "--around", "zzz-no-such"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 1
