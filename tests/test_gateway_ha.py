"""Gateway high-availability drills (ISSUE 16, parallel/dcn.py
GatewayJournal + T_SYNC + DcnClient endpoint lists): the durable
control plane's WAL edges (torn tail, corrupt clean slate, idempotent
resync), the fast failover drill (promotion within one lease window,
fenced resurrection, client endpoint failover), the no-standby seed
contract (EXIT_DISCONNECTED unchanged), the byte-compat contract (HA
off => nothing new observable), and the sessionless helpers' bounded
timeouts.  All numpy-only and seconds-scale; the randomized long-haul
version is ``tools/chaos_soak.py --kill-gateway``."""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import GatewayParams
from pytorch_distributed_tpu.parallel.dcn import (
    T_HELLO, DcnClient, DcnDisconnected, DcnGateway, GatewayJournal,
    _recv_frame, _rec_digest, _send_frame, fetch_status, parse_endpoints,
)
from tools.chaos_soak import ChunkLog, tagged_transition

GP = GatewayParams(enabled=True, lease_s=0.4, sync_s=0.05)


def make_gateway(tmp, log, role="primary", sync_from=None,
                 resume_term=None, gp=GP):
    clock = GlobalClock()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    return DcnGateway(store, clock, ActorStats(), put_chunk=log,
                      host="127.0.0.1", port=0, idle_deadline=30.0,
                      gateway_params=gp, log_dir=str(tmp),
                      ha_role=role, sync_from=sync_from,
                      resume_term=resume_term)


# ---------------------------------------------------------------------------
# WAL recovery edges
# ---------------------------------------------------------------------------

def test_wal_roundtrip_and_torn_tail(tmp_path):
    """A torn trailing record — the fsync victim a crash leaves — is
    skipped with a counted warning; everything before it recovers."""
    j = GatewayJournal(str(tmp_path))
    j.write_term(3)
    j.start_term(3)
    for i in range(5):
        j.append("slot", {"slot": i, "inc": 100 + i})
    path = j._wal_path(3)
    j.close()
    with open(path, "ab") as f:  # torn write: half a record, no newline
        f.write(b'{"seq": 99, "kind": "slot", "da')
    j2 = GatewayJournal(str(tmp_path))
    term, recs = j2.recover()
    assert term == 3
    assert [r["data"]["slot"] for r in recs] == [0, 1, 2, 3, 4]
    assert j2.recover_warnings >= 1
    assert j2.read_term() == 3
    j2.close()


def test_wal_corrupt_is_counted_clean_slate(tmp_path):
    """Garbage where the journal should be: recovery is a COUNTED clean
    slate (warn, continue at term 0), never a crash."""
    gwdir = tmp_path / "gateway"
    os.makedirs(gwdir)
    (gwdir / "TERM.json").write_text("{not json")
    (gwdir / "wal-00000007.jsonl").write_text("complete garbage\n\x00\x01")
    j = GatewayJournal(str(tmp_path))
    assert j.read_term() == 0
    term, recs = j.recover()
    assert term == 7  # the term FLOOR survives even an unreadable file
    assert recs == []
    assert j.recover_warnings >= 1
    j.close()


def test_wal_record_digest_rejects_tamper(tmp_path):
    """A bit-flipped record fails its digest and is dropped, counted."""
    j = GatewayJournal(str(tmp_path))
    j.write_term(1)
    j.start_term(1)
    j.append("slot", {"slot": 0, "inc": 5})
    j.append("slot", {"slot": 1, "inc": 6})
    path = j._wal_path(1)
    j.close()
    lines = open(path).read().splitlines()
    lines[0] = lines[0].replace('"inc": 5', '"inc": 500')
    open(path, "w").write("\n".join(lines) + "\n")
    j2 = GatewayJournal(str(tmp_path))
    _term, recs = j2.recover()
    assert [r["data"]["slot"] for r in recs] == [1]
    assert j2.recover_warnings >= 1
    j2.close()


def test_standby_applied_copy_is_idempotent(tmp_path):
    """The standby's applied-copy journal dedups by seq — a restart
    that re-pulls an overlapping suffix lands every record once."""
    j = GatewayJournal(str(tmp_path), standby=True)
    recs = [{"seq": i, "kind": "slot",
             "data": {"slot": 0, "inc": i},
             "sha": _rec_digest(i, "slot", {"slot": 0, "inc": i})}
            for i in range(1, 6)]
    for r in recs:
        assert j.apply(r) is True
    # the restart: a fresh standby journal recovers its own offset...
    j.close()
    j2 = GatewayJournal(str(tmp_path), standby=True)
    _t, seen = j2.recover()
    assert len(seen) == 5 and j2.seq == 5
    # ...and re-applying an overlapping suffix is a counted no-op
    assert all(j2.apply(r) is False for r in recs[2:])
    assert j2.seq == 5
    j2.close()


def test_seed_records_double_apply_no_double_count(tmp_path):
    """State records carry ABSOLUTE values applied through max(): a
    primary warm-restarting over a journal it already absorbed (or a
    standby re-pulling a suffix) never double-counts the ledger."""
    log = ChunkLog()
    gw = make_gateway(tmp_path, log)
    try:
        recs = [{"seq": 1, "kind": "state",
                 "data": {"tick_seq": {"0": 7}, "chunks_in": 40,
                          "lost": 3,
                          "ledger": {"ingested": 100, "shed": 2,
                                     "quarantined": 1}}},
                {"seq": 2, "kind": "slot", "data": {"slot": 0, "inc": 9}}]
        gw._seed_records(recs)
        first = dict(gw._ha_carry)
        lost = gw.failover_lost
        gw._seed_records(recs)  # the replay: must be a no-op
        assert gw._ha_carry == first
        assert gw.failover_lost == lost == 3
        assert gw._ha_carry["ingested"] == 100
        assert gw._inc_floor[0] == 9
    finally:
        gw.close()


def test_warm_restart_continues_term_and_ledger(tmp_path):
    """A primary restarted over its own journal bumps the term and
    carries the cumulative ledger forward instead of forgetting it."""
    log = ChunkLog()
    gw = make_gateway(tmp_path, log)
    t1 = gw.term
    gw._ha_append("state", {"tick_seq": {}, "chunks_in": 11, "lost": 0,
                            "ledger": {"ingested": 22, "shed": 0,
                                       "quarantined": 0}})
    gw.close()
    gw2 = make_gateway(tmp_path, log)
    try:
        assert gw2.term == t1 + 1
        snap = gw2.status_snapshot()["gateway"]
        assert snap["carry"]["chunks_in"] == 11
        assert snap["carry"]["ingested"] == 22
    finally:
        gw2.close()


# ---------------------------------------------------------------------------
# failover fast drill: promotion, client failover, fenced resurrection
# ---------------------------------------------------------------------------

def _hello(addr, slot=7, inc=None):
    """Raw HELLO: returns the reply dict, or None if the gateway
    dropped the connection (the standby/fenced refusal path)."""
    sock = socket.create_connection(addr, timeout=2.0)
    try:
        sock.settimeout(2.0)
        _send_frame(sock, T_HELLO, json.dumps(
            {"process_ind": slot,
             "incarnation": inc or time.time_ns()}).encode())
        try:
            _ftype, payload = _recv_frame(sock)
        except (ConnectionError, OSError):
            return None
        return json.loads(payload.decode())
    finally:
        sock.close()


def test_failover_promotion_fencing_and_resurrection(tmp_path):
    log = ChunkLog()
    primary = make_gateway(tmp_path, log)
    old_term = primary.term
    standby = make_gateway(tmp_path, log, role="standby",
                           sync_from=("127.0.0.1", primary.port))
    endpoints = [("127.0.0.1", primary.port),
                 ("127.0.0.1", standby.port)]
    client = DcnClient(endpoints, process_ind=0,
                       reconnect_timeout=10.0, heartbeat_interval=0.2)
    try:
        # pre-kill: sessions land on the primary; the standby REFUSES
        assert _hello(("127.0.0.1", standby.port)) is None
        assert standby.standby_refused >= 1
        for i in range(5):
            client.send_chunk([(tagged_transition(i), None)])
        deadline = time.monotonic() + 3.0  # journal the claims/state
        while primary.status_snapshot()["gateway"]["journal_seq"] < 2 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        primary.close()
        assert standby.promoted.wait(GP.lease_s * 4 + 2.0), \
            "standby never promoted"
        # the client fails over along its endpoint list and lives on
        for i in range(5, 10):
            client.send_chunk([(tagged_transition(i), None)])
        deadline = time.monotonic() + 8.0
        while (not {int(t) for t in log.tags}.issuperset(range(10))
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert client.failovers == 1
        assert client.address == ("127.0.0.1", standby.port)
        snap = standby.status_snapshot()["gateway"]
        assert snap["role"] == "primary"
        assert snap["term"] == old_term + 1
        assert snap["promotions"] == 1
        # the journaled incarnation floor crossed the failover: a
        # STALE incarnation for the claimed slot is refused on rejoin
        reply = _hello(("127.0.0.1", standby.port), slot=0, inc=1)
        assert reply is None or reply.get("error"), \
            f"stale incarnation re-claimed the slot: {reply}"
        # resurrection: the old primary comes back on its STALE term —
        # every session is refused, counted, and nothing is applied
        zsink = ChunkLog()
        zombie = make_gateway(tmp_path, zsink, resume_term=old_term)
        try:
            assert _hello(("127.0.0.1", zombie.port)) is None
            assert zombie.gateway_term_fenced >= 1
            assert zombie.chunks_in == 0 and zsink.tags == []
        finally:
            zombie.close()
        delivered = {int(t) for t in log.tags}
        assert delivered.issuperset(range(10)), \
            f"rows lost across failover: {sorted(delivered)}"
    finally:
        client.close()
        standby.close()


def test_no_standby_leg_exits_disconnected(tmp_path):
    """Without a standby the seed contract is untouched: a dead
    gateway still ends in DcnDisconnected after the redial budget."""
    log = ChunkLog()
    gw = make_gateway(tmp_path, log)
    client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                       reconnect_timeout=1.0, heartbeat_interval=0.2)
    try:
        client.send_chunk([(tagged_transition(0), None)])
        gw.close()
        with pytest.raises(DcnDisconnected):
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                client.send_chunk([(tagged_transition(1), None)])
                time.sleep(0.05)
        assert client.disconnected.is_set()
    finally:
        client.close()


# ---------------------------------------------------------------------------
# byte-compat: HA off => nothing new observable
# ---------------------------------------------------------------------------

def test_ha_disabled_is_byte_compatible(tmp_path):
    """With the plane off (the default) there is no STATUS block, no
    journal dir, and a single-endpoint client behaves as the seed."""
    clock = GlobalClock()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    gw = DcnGateway(store, clock, ActorStats(),
                    put_chunk=lambda items: None,
                    host="127.0.0.1", port=0,
                    log_dir=str(tmp_path))  # log_dir alone must not arm it
    client = DcnClient(("127.0.0.1", gw.port), process_ind=0)
    try:
        status = fetch_status(("127.0.0.1", gw.port))
        assert "gateway" not in status
        assert not os.path.exists(tmp_path / "gateway")
        assert client.endpoints == [("127.0.0.1", gw.port)]
        assert client.failovers == 0
        client.send_chunk([(tagged_transition(0), None)])
    finally:
        client.close()
        gw.close()


def test_parse_endpoints_forms():
    assert parse_endpoints(("h", 1)) == [("h", 1)]
    assert parse_endpoints([("a", 1), ("b", 2)]) == [("a", 1), ("b", 2)]
    assert parse_endpoints("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_endpoints(["a:1", ("b", 2)]) == [("a", 1), ("b", 2)]
    assert parse_endpoints("") == []


# ---------------------------------------------------------------------------
# sessionless helpers: bounded timeouts, single retry
# ---------------------------------------------------------------------------

def test_fetch_status_times_out_on_half_dead_gateway():
    """A listener that accepts and then says nothing — the half-dead
    gateway a monitor must NOT hang on: two bounded attempts, then a
    raised error, all within ~4x the per-call timeout."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    eaten = []

    def _eat():
        try:
            while True:
                conn, _ = srv.accept()
                eaten.append(conn)  # accept, never reply
        except OSError:
            pass

    t = threading.Thread(target=_eat, daemon=True)
    t.start()
    try:
        t0 = time.monotonic()
        with pytest.raises((ConnectionError, OSError)):
            fetch_status(srv.getsockname(), timeout=0.4)
        took = time.monotonic() - t0
        assert took < 4 * 0.4 + 1.0, \
            f"fetch_status hung {took:.1f}s on a silent gateway"
        time.sleep(0.3)  # let the accept loop catch up with the backlog
        assert len(eaten) == 2, \
            f"expected exactly one retry, saw {len(eaten)} attempts"
    finally:
        srv.close()
        for c in eaten:
            c.close()
