"""Sweep runner + Atari-57 suite list."""

import json
import os

from pytorch_distributed_tpu.envs.atari57 import ATARI_57, resolve_games
from pytorch_distributed_tpu.sweep import completed_games, run_sweep


def test_suite_list():
    assert len(ATARI_57) == 57
    assert len(set(ATARI_57)) == 57
    assert resolve_games("all") == ATARI_57
    assert resolve_games("pong,breakout") == ["pong", "breakout"]
    assert resolve_games("pong") == ["pong"]


def test_sweep_runs_and_resumes(tmp_path):
    overrides = dict(
        num_actors=1, steps=60, learn_start=16, batch_size=16,
        memory_size=1024, actor_sync_freq=20, param_publish_freq=5,
        learner_freq=20, evaluator_freq=30, early_stop=60,
        tester_nepisodes=1, visualize=False)
    results = run_sweep(1, ["chain"], overrides, root_dir=str(tmp_path),
                        backend="thread")
    assert len(results) == 1
    assert results[0]["game"] == "chain"
    assert results[0]["nepisodes"] == 1.0
    path = os.path.join(str(tmp_path), "sweep_results.jsonl")
    assert completed_games(str(tmp_path)) == {"chain"}
    # resumable: the finished game is skipped, file untouched
    size_before = os.path.getsize(path)
    again = run_sweep(1, ["chain"], overrides, root_dir=str(tmp_path),
                      backend="thread")
    assert again == []
    assert os.path.getsize(path) == size_before
    rec = json.loads(open(path).read().strip())
    assert rec["wall_s"] > 0
