"""Flow-control & graceful-degradation drills (ISSUE 11, utils/flow.py).

Three depths, mirroring the chaos suite's layering (TESTING.md
"Flow-control & overload drills"):

- units: token bucket, drop-oldest ring (+ provenance stamping), the
  overload governor's dwell/hysteresis/brownout ladder, shed_overflow,
  and resolve_flow's env contract;
- the wire: a real DcnClient <-> DcnGateway pair with the test holding
  the pressure lever — healthy acks carry NO credit field, throttled
  acks carry bucket-metered grants, a grant-0 client parks chunks and
  send_chunk RETURNS (non-blocking), and the heartbeat-vs-backpressure
  drill: a credit-blocked client rides out a full idle-deadline window
  on T_PING alone, is never reaped, and drains to a balanced ledger;
- satellites: the fleet_top ``flow:`` panel + STATUS block, the
  DEFAULT_RULES ``overload_shed`` alert, timeline LOUD kinds, and the
  local shed policies (QueueFeeder ring, device-ingest pending bound).

The randomized end-to-end versions are ``tools/chaos_soak.py --flood``
/ ``--slow-learner-ingest`` / ``--slow-slot``.
"""

import queue
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import FlowParams
from pytorch_distributed_tpu.memory.feeder import QueueFeeder
from pytorch_distributed_tpu.parallel.dcn import (
    DcnClient, DcnGateway, RemoteStats,
)
from pytorch_distributed_tpu.utils import flow
from pytorch_distributed_tpu.utils.experience import Transition, make_prov
from tools.chaos_soak import ChunkLog, tagged_transition


def _tr(tag=0, actor=None):
    t = tagged_transition(tag)
    if actor is not None:
        t = t._replace(prov=make_prov(actor, 0, 0, tag))
    return t


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Recorder:
    def __init__(self):
        self.events = []

    def record(self, kind, **kv):
        self.events.append((kind, kv))


class _Writer:
    def __init__(self):
        self.rows = []

    def scalar(self, tag, value, step=0, wall=None):
        self.rows.append((tag, float(value)))

    def flush(self):
        pass


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


class TestResolveFlow:
    def test_defaults_on_and_inert(self):
        fp = flow.resolve_flow()
        assert fp.enabled and fp.local_policy == "block"

    def test_bare_switch_and_field_overrides(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_FLOW", "0")
        assert not flow.resolve_flow().enabled
        monkeypatch.setenv("TPU_APEX_FLOW", "1")
        monkeypatch.setenv("TPU_APEX_FLOW_CLIENT_RING", "7")
        monkeypatch.setenv("TPU_APEX_FLOW_THROTTLE_AT", "0.5")
        monkeypatch.setenv("TPU_APEX_FLOW_LOCAL_POLICY", "shed")
        fp = flow.resolve_flow()
        assert (fp.enabled, fp.client_ring, fp.throttle_at,
                fp.local_policy) == (True, 7, 0.5, "shed")

    def test_input_never_mutated(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_FLOW_CLIENT_RING", "9")
        src = FlowParams()
        out = flow.resolve_flow(src)
        assert src.client_ring == FlowParams().client_ring
        assert out.client_ring == 9

    def test_export_env_round_trip(self, monkeypatch):
        for k in list(__import__("os").environ):
            if k.startswith("TPU_APEX_FLOW"):
                monkeypatch.delenv(k)
        fp = FlowParams(local_policy="shed", client_ring=11)
        flow.export_env(fp)
        try:
            child = flow.resolve_flow()
            assert child.local_policy == "shed"
            assert child.client_ring == 11
        finally:
            import os

            os.environ.pop("TPU_APEX_FLOW_LOCAL_POLICY", None)
            os.environ.pop("TPU_APEX_FLOW_CLIENT_RING", None)


class TestTokenBucket:
    def test_take_refill_cap(self):
        clk = _FakeClock()
        b = flow.TokenBucket(rate=10.0, burst=5.0, clock=clk)
        assert all(b.take() for _ in range(5))
        assert not b.take()
        clk.t = 0.5  # refill 5 tokens
        assert b.level() == pytest.approx(5.0)
        clk.t = 100.0  # cap at burst, not rate * dt
        assert b.level() == pytest.approx(5.0)


class TestDropOldestRing:
    def test_drop_oldest_counts_and_order(self):
        r = flow.DropOldestRing(max_chunks=3)
        for i in range(5):
            r.put([(_tr(i), None)])
        assert (r.dropped_chunks, r.dropped_rows) == (2, 2)
        assert [int(c[0][0].reward) for c in iter(r.pop, None)] == [2, 3, 4]

    def test_unpop_front_no_recount(self):
        r = flow.DropOldestRing(max_chunks=3)
        r.put([(_tr(0), None)])
        r.put([(_tr(1), None)])
        c = r.pop()
        r.unpop(c)
        assert r.dropped_chunks == 0
        assert int(r.pop()[0][0].reward) == 0  # front, not back

    def test_prov_stamped_drops(self):
        r = flow.DropOldestRing(max_chunks=1, owner=9)
        r.put([(_tr(0, actor=4), None), (_tr(1, actor=4), None)])
        r.put([(_tr(2), None)])   # prov-less: falls back to owner
        r.put([(_tr(3), None)])
        assert r.dropped_by_actor == {4: 2, 9: 1}
        assert r.buffered_rows == 1

    def test_high_water_bounded(self):
        r = flow.DropOldestRing(max_chunks=4)
        for i in range(50):
            r.put([(_tr(i), None)])
        assert len(r) == 4
        assert r.buffered_high <= 5  # momentary +1 before the evict


class TestShedOverflow:
    def test_trims_oldest_and_counts(self):
        pending = [_tr(i, actor=i % 2) for i in range(10)]
        counters = {}
        kept = flow.shed_overflow(pending, 6, counters)
        assert [int(t.reward) for t in kept] == list(range(4, 10))
        assert counters["shed_rows"] == 4
        assert counters["shed_by_actor:0"] == 2
        assert counters["shed_by_actor:1"] == 2

    def test_under_bound_untouched(self):
        pending = [_tr(i) for i in range(3)]
        counters = {}
        assert flow.shed_overflow(pending, 6, counters) is pending
        assert counters == {}


class TestOverloadGovernor:
    def _gov(self, **kw):
        clk = _FakeClock()
        params = FlowParams(dwell_s=1.0, recover_s=3.0,
                            brownout_dwell_s=5.0, **kw)
        rec, wr = _Recorder(), _Writer()
        g = flow.OverloadGovernor(params, recorder=rec, writer=wr,
                                  clock=clk)
        return g, clk, rec, wr

    def test_step_to_one_walks_the_ladder(self):
        """A pressure step to 1.0 still climbs ONE state per dwell —
        the timeline must show the ramp, not a teleport."""
        g, clk, rec, _ = self._gov()
        assert g.update(1.0) is None            # dwell starts
        clk.t = 1.0
        assert g.update(1.0) == "throttled"
        clk.t = 1.5
        assert g.update(1.0) is None            # next rung re-dwells
        clk.t = 2.0
        assert g.update(1.0) == "shedding"
        assert g.tier == 1
        assert [e[1]["why"] for e in rec.events] == ["escalate",
                                                     "escalate"]

    def test_brownout_ladder_climbs_and_resets(self):
        g, clk, _, wr = self._gov()
        for t, p in ((0, 1.0), (1, 1.0), (2, 1.0)):
            clk.t = float(t)
            g.update(p)
        assert (g.state, g.tier) == ("shedding", 1)
        clk.t = 7.0
        g.update(1.0)
        assert g.tier == 2
        clk.t = 12.0
        g.update(1.0)
        assert g.tier == 3
        clk.t = 17.0
        g.update(1.0)
        assert g.tier == 3                      # ladder tops out
        # recovery: below recover_at for recover_s steps down one state
        clk.t = 18.0
        g.update(0.1)
        clk.t = 21.0
        assert g.update(0.1) == "throttled"
        assert g.tier == 0                      # tier resets off the rung
        clk.t = 24.0
        assert g.update(0.1) == "healthy"
        states = [v for tag, v in wr.rows if tag == "flow/overload_state"]
        assert states == [1.0, 2.0, 2.0, 2.0, 1.0, 0.0]

    def test_hysteresis_band_holds_state(self):
        g, clk, _, _ = self._gov()
        clk.t = 0.0
        g.update(1.0)
        clk.t = 1.0
        g.update(1.0)                            # throttled
        for t in (2.0, 10.0, 60.0):
            clk.t = t
            assert g.update(0.6) is None         # recover_at < p < shed_at
        assert g.state == "throttled"

    def test_recovery_redwells_per_step(self):
        g, clk, _, _ = self._gov()
        for t in (0.0, 1.0, 2.0):
            clk.t = t
            g.update(1.0)                        # shedding
        clk.t = 3.0
        g.update(0.0)
        clk.t = 6.0
        assert g.update(0.0) == "throttled"
        clk.t = 7.0
        assert g.update(0.0) is None             # healthy needs its own 3s
        clk.t = 9.0
        assert g.update(0.0) == "healthy"


class TestGatewayFlow:
    def _flow(self, pressure=0.0, **kw):
        clk = _FakeClock()
        params = FlowParams(dwell_s=0.0, recover_s=0.0, **kw)
        cell = {"p": pressure}
        gf = flow.GatewayFlow(params, pressure=lambda: cell["p"],
                              clock=clk, update_every=0.0)
        return gf, clk, cell

    def test_healthy_no_credit_field_admits_all(self):
        gf, _, _ = self._flow()
        assert gf.grant(0) is None
        for _ in range(50):
            assert gf.admit(0, 16)
        assert gf.shed_chunks == 0

    def test_throttled_grants_bucket_metered(self):
        gf, clk, cell = self._flow(credits_throttled=4)
        cell["p"] = 1.0
        clk.t = 0.1
        gf.refresh()                              # healthy -> throttled
        assert gf.governor.state == "throttled"
        g = gf.grant(0)
        assert g is not None and 0 <= g <= 4

    def test_shedding_grants_zero_tier3_sheds(self):
        gf, clk, cell = self._flow(bucket_rate=0.0, bucket_burst=0.0,
                                   brownout_dwell_s=0.0)
        cell["p"] = 1.0
        for i in range(1, 6):
            clk.t = i * 0.1
            gf.refresh()
        assert gf.governor.state == "shedding"
        assert gf.governor.tier == 3
        assert gf.grant(2) == 0
        assert not gf.admit(2, 8)                 # dry bucket at tier 3
        assert gf.shed_rows == {2: 8}
        assert gf.shed_chunks == 1

    def test_conservation_unknown_without_reports(self):
        gf, _, _ = self._flow()
        assert "balanced" not in gf.conservation()

    def test_conservation_balances_and_is_idempotent(self):
        gf, _, _ = self._flow()
        gf.note_ingested(90)
        report = {"minted": 100, "acked": 90, "dropped": 8, "buffered": 2}
        gf.on_client_report(0, report)
        gf.on_client_report(0, report)            # retransmit: cumulative
        c = gf.conservation()
        assert c["balanced"] and c["minted"] == 100
        # garbage sanitizes to zeros — an empty slot, never a false alarm
        gf.on_client_report(1, {"minted": "garbage"})
        c2 = gf.conservation()
        assert c2["balanced"] and c2["minted"] == 100
        assert c2["reporting_slots"] == [0, 1]

    def test_status_block_shape(self):
        gf, _, _ = self._flow()
        gf.on_client_report(0, {"minted": 10, "dropped": 3})
        blk = gf.status_block(quarantined=1)
        assert blk["state"] == "healthy"
        assert blk["drop_share"] == {"0": 1.0}
        assert blk["conservation"]["quarantined"] == 1


# ---------------------------------------------------------------------------
# the wire
# ---------------------------------------------------------------------------


@pytest.fixture()
def wire():
    """Gateway + pressure lever; the governor is driven DIRECTLY by the
    tests (refresh pinned off) so wire assertions are deterministic."""
    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(8)
    store.publish(np.zeros(8, dtype=np.float32))
    log = ChunkLog()
    gw = DcnGateway(store, clock, stats, put_chunk=log,
                    host="127.0.0.1", port=0, idle_deadline=30.0,
                    flow_params=FlowParams(dwell_s=0.0, recover_s=0.0),
                    pressure=lambda: 0.0)
    gw.flow._next_update = time.monotonic() + 3600  # tests drive it
    yield gw, log, clock
    gw.close()


def _chunk(tag=0, n=1):
    return [(tagged_transition(tag + i), None) for i in range(n)]


class TestCreditWire:
    def test_healthy_ack_carries_no_credits(self, wire):
        gw, log, _ = wire
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        client.send_chunk(_chunk(0))
        assert client.credits is None             # absent field = unlimited
        assert len(log.tags) == 1
        client.close()

    def test_throttled_grant_rides_ack_and_meters(self, wire):
        gw, log, _ = wire
        gw.flow.governor.update(1.0)              # dwell 0: -> throttled
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        client.send_chunk(_chunk(0))
        assert client.credits is not None
        assert 0 <= client.credits <= gw.flow.params.credits_throttled
        client.close()

    def test_grant_zero_parks_nonblocking(self, wire):
        gw, log, _ = wire
        gov = gw.flow.governor
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        client.send_chunk(_chunk(0))              # healthy: delivered
        assert client.credits is None
        gov.update(1.0)
        gov.update(1.0)                           # -> shedding: grant 0
        client.send_chunk(_chunk(1))              # delivered; ack grants 0
        assert client.credits == 0
        t0 = time.perf_counter()
        for i in range(2, 6):
            client.send_chunk(_chunk(i))
        # the deadlock the plane exists to prevent: a blocked client's
        # send RETURNS (the actor loop keeps publishing progress marks,
        # so the PR-5 hang watchdog never sees a stale actor)
        assert time.perf_counter() - t0 < 0.5
        assert len(client.flow_ring) == 4
        assert len(log.tags) == 2
        # recovery: governor steps down, the next send drains the ring
        gov.update(0.0)
        gov.update(0.0)                           # -> healthy
        client.tick()                             # fresh ack clears credits
        assert client.credits is None
        client.send_chunk(_chunk(9))
        assert len(log.tags) == 7
        assert client.flow_ring.dropped_rows == 0
        client.close()

    def test_heartbeat_vs_backpressure_never_reaped(self):
        """THE ISSUE-11 satellite drill: a credit-blocked client keeps
        answering T_PING through a full gateway idle-deadline window —
        throttled must never read as dead (no reap, no reconnect, no
        disconnect), and once pressure clears the ring drains to a
        conservation-balanced ledger."""
        clock = GlobalClock()
        stats = ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        log = ChunkLog()
        cell = {"p": 1.0}
        gw = DcnGateway(store, clock, stats, put_chunk=log,
                        host="127.0.0.1", port=0, idle_deadline=1.0,
                        flow_params=FlowParams(dwell_s=0.0,
                                               recover_s=0.0),
                        pressure=lambda: cell["p"])
        gw.flow._update_every = 0.0               # every ack re-evaluates
        cell["p"] = 0.0                           # calm while connecting
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0.2)
        try:
            client.send_chunk(_chunk(0))          # healthy: delivered
            cell["p"] = 1.0
            client.send_chunk(_chunk(1))          # walks the governor up;
            assert client.credits == 0            # its ack lands grant 0
            for i in range(2, 6):
                client.send_chunk(_chunk(i))      # parked client-side
            assert len(client.flow_ring) == 4
            # ride out TWO idle-deadline windows on heartbeats alone
            time.sleep(2.2)
            assert not client.disconnected.is_set()
            assert client.reconnects == 0
            assert 0 in gw.active_slots           # never reaped
            # pressure clears -> ping acks walk the governor down and
            # re-grant; the next send drains the parked backlog
            cell["p"] = 0.0
            time.sleep(0.8)
            client.send_chunk(_chunk(9))
            assert len(client.flow_ring) == 0
            client.tick()                         # report flow counters
            cons = gw.flow.conservation()
            assert cons["balanced"], cons
            assert cons["minted"] == client.flow_minted_rows == 7
            assert client.flow_ring.dropped_rows == 0
            assert sorted(log.tags) == [0, 1, 2, 3, 4, 5, 9]
        finally:
            client.close()
            gw.close()

    def test_ring_overflow_counted_into_ledger(self, wire, monkeypatch):
        monkeypatch.setenv("TPU_APEX_FLOW_CLIENT_RING", "2")
        gw, log, _ = wire
        gov = gw.flow.governor
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        client.send_chunk(_chunk(0))              # healthy: delivered
        gov.update(1.0)
        gov.update(1.0)                           # shedding
        client.send_chunk(_chunk(11))             # delivered; ack: grant 0
        for i in range(2, 6):
            client.send_chunk(_chunk(10 + i))     # 4 parked into ring of 2
        assert client.flow_ring.dropped_rows == 2
        gov.update(0.0)
        gov.update(0.0)
        client.tick()
        client.send_chunk(_chunk(30))             # drains the 2 survivors
        client.tick()
        cons = gw.flow.conservation()
        assert cons["balanced"], cons
        assert cons["dropped_client"] == 2
        client.close()

    def test_brownout_tier_latches_and_sheds_stats(self, wire):
        gw, log, _ = wire
        flow.reset_shed_state()
        gov = gw.flow.governor
        gov.update(1.0)
        gov.update(1.0)
        gov.tier = 1                              # telemetry rung
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        try:
            client.tick()                         # reply carries brownout
            assert flow.brownout_tier() == 1
            rstats = RemoteStats(client)
            rstats.add(nepisodes=1.0)
            assert flow.shed_counts().get("stats") == 1
            # recovery clears the latch through the same reply path
            gov.update(0.0)
            gov.update(0.0)
            gov.tier = 0
            client.tick()
            assert flow.brownout_tier() == 0
        finally:
            client.close()
            flow.reset_shed_state()

    def test_disabled_plane_is_preflow(self, wire, monkeypatch):
        monkeypatch.setenv("TPU_APEX_FLOW", "0")
        gw, log, _ = wire
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        gw.flow.governor.update(1.0)
        gw.flow.governor.update(1.0)              # gateway sheds/grants 0
        client.send_chunk(_chunk(0))
        client.send_chunk(_chunk(1))
        # a disabled client ignores credit fields entirely: every send
        # is the plain blocking RPC, nothing parks
        assert len(client.flow_ring) == 0
        assert len(log.tags) == 2
        client.close()


# ---------------------------------------------------------------------------
# satellites: fleet_top panel, alert rule, timeline kinds, local policies
# ---------------------------------------------------------------------------


class TestFleetTopFlowPanel:
    def test_status_carries_flow_block_over_wire(self, wire):
        from tools.fleet_top import fetch_status, flow_line, render

        gw, _, _ = wire
        gw.flow.governor.update(1.0)              # throttled
        gw.flow.on_client_report(0, {"minted": 10, "dropped": 4})
        status = fetch_status(("127.0.0.1", gw.port))
        assert status["flow"]["state"] == "throttled"
        line = flow_line(status)
        assert line and "THROTTLED" in line and "credits" in line
        assert "s0=4" in line                     # the drop counter
        assert "flow:" in render(status)

    def test_panel_absent_without_plane(self):
        from tools.fleet_top import flow_line

        assert flow_line({"learner_step": 0}) is None

    def test_imbalance_is_loud(self):
        from tools.fleet_top import flow_line

        gf, *_ = TestGatewayFlow()._flow()
        gf.on_client_report(0, {"minted": 100, "dropped": 1})
        line = flow_line({"flow": gf.status_block()})
        assert "IMBALANCED" in line


class TestAlertAndTimelineWiring:
    def test_default_rules_watch_overload(self):
        from pytorch_distributed_tpu.utils.telemetry import (
            DEFAULT_RULES, parse_rules,
        )

        rules = parse_rules(DEFAULT_RULES)
        byname = {r.name: r for r in rules}
        assert "overload_shed" in byname
        assert byname["overload_shed"].tag == "flow/overload_state"

    def test_timeline_loud_kinds_and_prefixes(self):
        import tools.timeline as tl

        assert {"overload", "flow-shed", "brownout"} <= tl._LOUD_KINDS
        assert any(p.startswith("flow/")
                   for p in tl._DEFAULT_SCALAR_PREFIXES)

    def test_governor_transitions_hit_recorder_and_scalars(self):
        clk = _FakeClock()
        rec, wr = _Recorder(), _Writer()
        g = flow.OverloadGovernor(FlowParams(dwell_s=0.0), recorder=rec,
                                  writer=wr, clock=clk)
        g.update(1.0)
        assert rec.events[0][0] == "overload"
        assert ("flow/overload_state", 1.0) in wr.rows


class TestFeederShedPolicy:
    def test_shed_never_blocks_and_counts(self):
        q = queue.Queue(maxsize=1)
        f = QueueFeeder(q, chunk=1)
        f.configure_flow(FlowParams(local_policy="shed", feeder_ring=2))
        t0 = time.perf_counter()
        for i in range(5):
            f.feed(_tr(i))                        # chunk=1: flush per feed
        assert time.perf_counter() - t0 < 0.5     # never blocked
        # 1 delivered, ring holds 2, 2 dropped oldest
        assert q.qsize() == 1
        assert f.flow_dropped_rows == 2
        q.get_nowait()
        f.feed(_tr(9))                            # drains oldest-first
        assert q.qsize() == 1

    def test_block_default_untouched(self):
        q = queue.Queue(maxsize=4)
        f = QueueFeeder(q, chunk=1)
        f.configure_flow(FlowParams(local_policy="block"))
        assert f._flow_ring is None
        f.feed(_tr(0))
        assert q.qsize() == 1

    def test_clone_carries_policy_pickle_drops_ring(self):
        q = queue.Queue(maxsize=1)
        f = QueueFeeder(q, chunk=1)
        f.configure_flow(FlowParams(local_policy="shed", feeder_ring=2))
        assert f.clone()._flow_ring is not None
        # spawn-pickle contract: the ring (its lock, and THIS process's
        # backlog) never rides into the child — the harness re-engages
        # the policy via configure_flow (the queue itself is an mp queue
        # in production; a local queue.Queue stands in here, so inspect
        # the state dict rather than round-tripping the whole feeder)
        assert f.__getstate__()["_flow_ring"] is None
        assert f.__getstate__()["_flow_params"] is not None


class TestOverloadAcceptance:
    """The ISSUE-11 acceptance drills through tools/chaos_soak.py —
    the PRODUCTION path end-to-end (live backlog pressure, credits on
    acks, client rings, the ``overload`` alert via mission control).
    All three scenarios ride the slow marker since ISSUE 12's budget
    thinning (one verdict code path, CLI exercised nightly); tier-1
    keeps the wire-level credit/ledger drills above."""

    @pytest.mark.slow
    @pytest.mark.timeout(120)
    def test_flood_drill_zero_violations(self):
        from tools.chaos_soak import soak

        report = soak(seconds=10.0, flood=True, verbose=False)
        assert report["violations"] == [], report["violations"]
        blk = report["flow"]
        assert blk["balanced"]                    # conservation, exact
        assert blk["transitions"] > 0             # governor engaged
        assert blk["dropped_client"] > 0          # overload had a cost...
        assert blk["drop_share"]                  # ...and it has names
        assert report["alerts"]["fired"] == ["overload"]
        assert report["alerts"]["unresolved"] == []

    @pytest.mark.slow
    def test_slow_ingest_drill_zero_violations(self):
        from tools.chaos_soak import soak

        report = soak(seconds=12.0, slow_ingest=3.0, verbose=False)
        assert report["violations"] == [], report["violations"]
        assert report["flow"]["balanced"]

    @pytest.mark.slow
    def test_slow_slot_drill_fairness(self):
        from tools.chaos_soak import soak

        report = soak(seconds=12.0, slow_slot=True, verbose=False)
        assert report["violations"] == [], report["violations"]
        # the runaway (slot 0) pays for the overload, not its neighbours
        share = report["flow"]["drop_share"]
        assert float(share.get("0", 0.0)) > 0.9, share


class TestDeviceIngestShedPolicy:
    @pytest.mark.filterwarnings("ignore")
    def test_pending_bounded_under_shed(self):
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplayIngest,
        )

        ing = DeviceReplayIngest(64, (4,), state_dtype=np.float32)
        ing.attach()
        ing.configure_flow(FlowParams(local_policy="shed",
                                      max_pending_rows=8))
        ing._q = queue.Queue()                    # sync queue: no mp lag
        feeder = ing.make_feeder(chunk=4)
        for i in range(32):
            feeder.feed(Transition(
                state0=np.zeros(4, dtype=np.float32), action=np.int32(0),
                reward=np.float32(0.0), gamma_n=np.float32(0.99),
                state1=np.zeros(4, dtype=np.float32),
                terminal1=np.float32(0.0),
                prov=make_prov(3, 0, 0, i)))
        ing.drain()
        assert ing.flow_counters["shed_rows"] == 24
        assert ing.flow_counters["shed_by_actor:3"] == 24
        assert len(ing._pending) <= 8
