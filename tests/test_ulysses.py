"""Ulysses all-to-all sequence parallelism: equals dense attention and the
ring on the 8-virtual-device CPU mesh, and the DTQN learner trains with it
(parallel_params.sp_attention = "ulysses")."""

import numpy as np
import pytest

import jax.numpy as jnp

from pytorch_distributed_tpu.ops.ring_attention import (
    full_attention, ring_attention,
)
from pytorch_distributed_tpu.ops.ulysses_attention import ulysses_attention
from pytorch_distributed_tpu.parallel.mesh import make_mesh


def _qkv(B=4, H=4, T=32, D=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(B, H, T, D))
                             .astype(np.float32)) for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
def test_matches_full_attention(causal):
    mesh = make_mesh(dp_size=2, sp_size=4)
    q, k, v = _qkv()
    out_u = ulysses_attention(q, k, v, mesh, causal=causal)
    out_full = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_full),
                               rtol=1e-4, atol=1e-5)


def test_matches_ring():
    mesh = make_mesh(dp_size=1, sp_size=8)
    q, k, v = _qkv(B=2, H=8, T=64)
    out_u = ulysses_attention(q, k, v, mesh, causal=True)
    out_r = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                               rtol=1e-4, atol=1e-5)


def test_head_divisibility_guard():
    mesh = make_mesh(dp_size=2, sp_size=4)
    q, k, v = _qkv(H=2)  # 2 heads on sp=4
    with pytest.raises(AssertionError, match="divisible"):
        ulysses_attention(q, k, v, mesh)


def test_dtqn_window_q_matches_local():
    import jax

    from pytorch_distributed_tpu.models.dtqn import (
        DtqnMlpModel, with_ulysses_attention,
    )

    mesh = make_mesh(dp_size=2, sp_size=4)
    model = DtqnMlpModel(action_space=3, state_shape=(4,), window=16,
                         dim=32, heads=4, depth=2, norm_val=1.0)
    obs0 = jnp.zeros((2, 4))
    params = model.init(jax.random.PRNGKey(0), obs0)
    seq = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 4))
    q_local = model.apply(params, seq, method=model.window_q)
    umodel = with_ulysses_attention(model, mesh)
    q_u = umodel.apply(params, seq, method=umodel.window_q)
    np.testing.assert_allclose(np.asarray(q_u), np.asarray(q_local),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
@pytest.mark.timeout(1200)
def test_dtqn_ulysses_learner_runs(tmp_path):
    """The sp>1 Ulysses path end to end: dp2 x sp4 mesh, DTQN attention
    swapped for the all-to-all, short topology run."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        15, root_dir=str(tmp_path), num_actors=1, steps=40, learn_start=4,
        batch_size=8, memory_size=1024, seq_len=15, seq_overlap=7,
        nstep=3, actor_sync_freq=20, param_publish_freq=5, learner_freq=10,
        evaluator_freq=30, early_stop=60, dp_size=2, sp_size=4,
        sp_attention="ulysses")
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 40
