"""Tier-1 oracles for the sharded prioritized-replay plane (ISSUE 20).

The trust anchor is bit-parity: a 1-shard plane must be BIT-identical
to the single-host ``PrioritizedReplay`` path (sampled indices, IS
weights, |TD| write-backs, priorities) — the PR-14 N=1 oracle pattern
on the replay plane — and a kill-at-round-K plane must sample exactly
like a fresh plane built from the surviving shards only.  The rest is
the fault ledger: lease expiry within one window, exact conservation
through the loss (shard_lost + route_dropped counted), fenced stale
write-backs (counted, never applied), and the rejoin barrier
(ingest-first, sample-after-activate)."""

import time

import numpy as np
import pytest

from pytorch_distributed_tpu.config import ShardParams
from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.memory.shard_plane import (
    SSTAT_DEAD, SSTAT_OK, LocalShard, LoopbackShardChannel,
    ShardRegistry, _pack_sprio, _pack_ssample, _pack_ssample_reply,
    _unpack_ssample, _unpack_ssample_reply, build_loopback_plane,
    resolve_shard, sharding_active,
)
from pytorch_distributed_tpu.utils.experience import (
    REPLAY_FIELDS, Transition, make_prov,
)

GEOM = dict(state_shape=(4,), state_dtype=np.float32,
            action_shape=(), action_dtype=np.int32)


def _tr(i, actor=None):
    prov = (make_prov(actor, i % 8, 0, i) if actor is not None else None)
    return Transition(
        state0=np.full((4,), i, dtype=np.float32),
        action=np.int32(i % 4),
        reward=np.float32(i),
        gamma_n=np.float32(0.99),
        state1=np.full((4,), i + 1, dtype=np.float32),
        terminal1=np.float32(i % 7 == 0),
        prov=prov)


def _plane(shards, capacity, lease_s=30.0, **kw):
    return build_loopback_plane(
        ShardParams(shards=shards, lease_s=lease_s),
        capacity=capacity, priority_exponent=0.6,
        importance_weight=0.4, importance_anneal_steps=50,
        **GEOM, **kw)


def _expire(reg, plane, sid, rng, timeout=5.0):
    """Drive sampling until the dead shard's lease expires (survivor
    polls renew their own leases; the dead one goes silent)."""
    deadline = time.monotonic() + timeout
    while any(m["shard"] == sid
              for m in reg.live_members(include_joining=True)):
        plane.sample(4, rng)
        assert time.monotonic() < deadline, \
            f"shard {sid} never expired within {timeout}s"
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# the bit-parity oracles
# ---------------------------------------------------------------------------

class TestOneShardParity:
    def test_bit_identical_to_single_host_per(self):
        per = PrioritizedReplay(
            capacity=64, priority_exponent=0.6, importance_weight=0.4,
            importance_anneal_steps=50, **GEOM)
        plane, shards, reg = _plane(1, 64)
        assert plane.shard_capacity == 64  # 1 shard owns the full budget
        for i in range(40):
            pr = None if i % 3 == 0 else float(i % 5) + 0.5
            per.feed(_tr(i, actor=i % 3), pr)
            plane.feed(_tr(i, actor=i % 3), pr)
        rng_a = np.random.default_rng(7)
        rng_b = np.random.default_rng(7)
        for rnd in range(6):
            ba = per.sample(16, rng_a)
            bb = plane.sample(16, rng_b)
            # indices, IS weights, and every replay column: BIT-equal
            np.testing.assert_array_equal(ba.index, bb.index)
            assert ba.index.dtype == bb.index.dtype == np.int32
            np.testing.assert_array_equal(ba.weight, bb.weight)
            assert bb.weight.dtype == np.float32
            for f in REPLAY_FIELDS:
                np.testing.assert_array_equal(
                    getattr(ba, f), getattr(bb, f))
            # provenance of the sampled rows matches the single-host read
            np.testing.assert_array_equal(
                per.provenance_of(ba.index),
                plane.provenance_of(bb.index))
            # |TD| write-back rides the same math (incl. max_priority,
            # exercised by the None-priority feeds above)
            td = (np.sin(np.arange(16) + rnd) * 3.0).astype(np.float32)
            per.update_priorities(ba.index, td)
            plane.update_priorities(bb.index, td)
        np.testing.assert_array_equal(per.priority_leaves(),
                                      plane.priority_leaves())
        # nothing was fenced on the healthy path
        assert reg.stale_writeback_rejected == 0
        assert reg.route_dropped == 0

    def test_write_back_then_resample_stays_identical(self):
        per = PrioritizedReplay(
            capacity=32, priority_exponent=0.6, importance_weight=0.4,
            importance_anneal_steps=50, **GEOM)
        plane, _, _ = _plane(1, 32)
        for i in range(20):
            per.feed(_tr(i))
            plane.feed(_tr(i))
        rng_a, rng_b = (np.random.default_rng(3),
                        np.random.default_rng(3))
        ba, bb = per.sample(8, rng_a), plane.sample(8, rng_b)
        per.update_priorities(ba.index, np.zeros(8))
        plane.update_priorities(bb.index, np.zeros(8))
        ba, bb = per.sample(8, rng_a), plane.sample(8, rng_b)
        np.testing.assert_array_equal(ba.index, bb.index)
        np.testing.assert_array_equal(ba.weight, bb.weight)


class TestKillAtRoundK:
    def test_survivors_match_fresh_survivor_plane(self):
        plane, shards, reg = _plane(3, 96)
        for i in range(60):
            plane.feed(_tr(i, actor=i))
        rng = np.random.default_rng(5)
        for rnd in range(4):
            b = plane.sample(8, rng)
            plane.update_priorities(
                b.index, np.cos(np.arange(8) + rnd) * 2.0)
        # kill shard 1 mid-life: the mass vector drops it on the next
        # refresh, before the lease even expires
        shards[1].alive = False
        # oracle: a FRESH plane built from the survivors' snapshots
        fresh_plane, fresh_shards, _ = _plane(3, 96, shard_ids=[0, 2])
        fresh_shards[0].restore(shards[0].snapshot())
        fresh_shards[2].restore(shards[2].snapshot())
        fresh_plane._samples_drawn = plane._samples_drawn
        rng_a = np.random.default_rng(11)
        rng_b = np.random.default_rng(11)
        for _ in range(3):
            ba = plane.sample(8, rng_a)
            bb = fresh_plane.sample(8, rng_b)
            np.testing.assert_array_equal(ba.index, bb.index)
            np.testing.assert_array_equal(ba.weight, bb.weight)
            for f in REPLAY_FIELDS:
                np.testing.assert_array_equal(
                    getattr(ba, f), getattr(bb, f))
        # no survivor row decodes into the dead shard's id range
        assert not np.any((ba.index >= plane.shard_capacity)
                          & (ba.index < 2 * plane.shard_capacity))


# ---------------------------------------------------------------------------
# the fault ledger
# ---------------------------------------------------------------------------

class TestShardLoss:
    def test_lease_expiry_keeps_conservation_exact(self):
        plane, shards, reg = _plane(2, 32, lease_s=0.05)
        minted = 0
        for i in range(20):
            plane.feed(_tr(i))
            minted += 1
        assert shards[0].ingested_rows == shards[1].ingested_rows == 10
        shards[1].alive = False
        # rows routed at the dead-but-unexpired shard are counted drops
        for i in range(4):
            plane.feed(_tr(100 + i))
            minted += 1
        led = reg.ledger()
        assert (led["ingested"] + led["shard_lost"]
                + led["route_dropped"]) == minted
        rng = np.random.default_rng(1)
        _expire(reg, plane, 1, rng)
        assert reg.leases_expired == 1
        assert reg.shard_lost_rows == 10  # the dead shard's acked rows
        led = reg.ledger()
        assert (led["ingested"] + led["shard_lost"]
                + led["route_dropped"]) == minted
        # post-loss ingest drains onto the survivor, ledger still exact
        for i in range(6):
            plane.feed(_tr(200 + i))
            minted += 1
        led = reg.ledger()
        assert (led["ingested"] + led["shard_lost"]
                + led["route_dropped"]) == minted
        sb = reg.status_block()
        assert sb["degraded"] is True
        assert sb["counters"]["shard_lost_rows"] == 10
        # and sampling still answers (over the survivor alone)
        b = plane.sample(8, rng)
        assert np.all(b.index < plane.shard_capacity)

    def test_stale_writeback_is_counted_never_applied(self):
        plane, shards, reg = _plane(2, 32, lease_s=0.05)
        for i in range(16):
            plane.feed(_tr(i))
        rng = np.random.default_rng(2)
        b = plane.sample(32, rng)
        dead_rows = int(np.sum(b.index >= plane.shard_capacity))
        assert dead_rows > 0  # the batch straddles both shards
        shards[1].alive = False
        leaves_before = shards[1].per.priority_leaves().copy()
        _expire(reg, plane, 1, rng)
        plane.update_priorities(b.index, np.full(32, 9.9, np.float32))
        # the dead shard's rows were fenced at the registry: counted,
        # and its tree is untouched
        assert reg.stale_writeback_rejected == dead_rows
        np.testing.assert_array_equal(
            shards[1].per.priority_leaves(), leaves_before)
        # the survivor's rows DID apply
        applied = shards[0].per.sum_tree.get(
            (b.index[b.index < plane.shard_capacity]).astype(np.int64))
        np.testing.assert_allclose(
            applied, (9.9 + 1e-6) ** 0.6, rtol=1e-6)

    def test_zombie_generation_rejected_at_the_shard(self):
        plane, shards, reg = _plane(2, 32)
        for i in range(8):
            plane.feed(_tr(i))
        leaves = shards[0].per.priority_leaves().copy()
        ok = shards[0].write_prio(np.array([0, 1]),
                                  np.array([5.0, 5.0]), generation=999)
        assert ok is False
        assert shards[0].stale_rejected == 2
        np.testing.assert_array_equal(
            shards[0].per.priority_leaves(), leaves)

    def test_double_lease_newer_incarnation_fences(self):
        reg = ShardRegistry(ShardParams(shards=2, lease_s=30.0))
        g1 = reg.acquire(0, incarnation=1)
        assert g1["status"] == "ok"
        reg.renew(0, g1["generation"], {"ingested": 7})
        # equal incarnation: refused (the holder is still live)
        assert reg.acquire(0, incarnation=1)["status"] == "refused"
        # newer incarnation: evicts + fences the half-open predecessor
        g2 = reg.acquire(0, incarnation=2)
        assert g2["status"] == "ok"
        assert g2["generation"] > g1["generation"]
        assert reg.lease_fenced == 1
        assert reg.shard_lost_rows == 7
        assert reg.renew(0, g1["generation"])["status"] == "expired"


class TestRejoinBarrier:
    def test_joining_gets_ingest_but_no_sample_mass(self):
        plane, shards, reg = _plane(2, 32, lease_s=0.05)
        for i in range(12):
            plane.feed(_tr(i))
        rng = np.random.default_rng(4)
        shards[1].alive = False
        _expire(reg, plane, 1, rng)
        # rejoin at a fresh generation: joining (the epoch barrier)
        per2 = PrioritizedReplay(
            capacity=plane.shard_capacity, priority_exponent=0.6,
            importance_weight=0.4, importance_anneal_steps=50, **GEOM)
        ns = LocalShard(1, per2)
        grant = reg.acquire(1, incarnation=2,
                            capacity=plane.shard_capacity)
        assert grant["status"] == "ok" and grant["joining"] is True
        ns.generation = int(grant["generation"])
        plane.attach_channel(1, LoopbackShardChannel(ns, reg))
        # membership resolved: no longer degraded (the alert clears)
        assert reg.status_block()["degraded"] is False
        # ingest routes to the joiner immediately (rebalance)...
        for i in range(8):
            plane.feed(_tr(300 + i))
        assert ns.ingested_rows > 0
        # ...but sampling excludes it until activate
        b = plane.sample(16, rng)
        assert np.all(b.index < plane.shard_capacity)
        assert reg.activate(1, ns.generation)["status"] == "ok"
        assert reg.joins_completed == 1
        b = plane.sample(64, rng)
        assert np.any(b.index >= plane.shard_capacity)

    def test_fresh_shard_is_a_full_member_at_once(self):
        reg = ShardRegistry(ShardParams(shards=2, lease_s=30.0))
        g = reg.acquire(0, incarnation=1)
        assert g["joining"] is False

    def test_join_timeout_cancels_the_ghost(self):
        reg = ShardRegistry(ShardParams(shards=2, lease_s=0.05,
                                        join_timeout_s=0.05))
        g1 = reg.acquire(0, incarnation=1)
        # expire it, then rejoin and never activate
        time.sleep(0.12)
        assert reg.live_members(include_joining=True) == []
        g2 = reg.acquire(0, incarnation=2)
        assert g2["joining"] is True
        deadline = time.monotonic() + 5.0
        while any(m["shard"] == 0
                  for m in reg.live_members(include_joining=True)):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        assert reg.joins_timed_out == 1


class TestRebalance:
    def test_route_rebuilds_on_membership_change(self):
        plane, shards, reg = _plane(2, 32, lease_s=0.05)
        for i in range(8):
            plane.feed(_tr(i))
        epoch0 = reg.route_epoch
        rebal0 = reg.rebalances
        shards[1].alive = False
        rng = np.random.default_rng(6)
        _expire(reg, plane, 1, rng)
        assert reg.route_epoch > epoch0
        assert reg.rebalances > rebal0
        # every post-change row lands on the survivor
        before = shards[0].ingested_rows
        for i in range(5):
            plane.feed(_tr(500 + i))
        assert shards[0].ingested_rows == before + 5

    def test_actor_slot_routing_is_stable(self):
        plane, shards, reg = _plane(2, 32)
        # a fixed actor slot always lands on the same shard
        for i in range(6):
            plane.feed(_tr(i, actor=4))
        assert {shards[0].ingested_rows, shards[1].ingested_rows} \
            == {0, 6}


# ---------------------------------------------------------------------------
# codecs + config plane
# ---------------------------------------------------------------------------

class TestCodecs:
    def test_ssample_roundtrip(self):
        sid, gen, values = _unpack_ssample(_pack_ssample(3, 17))
        assert (sid, gen, len(values)) == (3, 17, 0)
        vals = np.array([0.5, 1.25], np.float64)
        sid, gen, out = _unpack_ssample(_pack_ssample(1, 2, vals))
        np.testing.assert_array_equal(out, vals)

    def test_ssample_reply_roundtrip_via_local_shard(self):
        plane, shards, reg = _plane(1, 16)
        for i in range(6):
            plane.feed(_tr(i, actor=i))
        total = shards[0].per.sum_tree.total
        reply = _unpack_ssample_reply(shards[0].handle_ssample(
            _pack_ssample(0, shards[0].generation,
                          np.array([total * 0.1, total * 0.9]))))
        assert reply["status"] == SSTAT_OK
        assert reply["mass"]["size"] == 6
        assert reply["mass"]["ingested"] == 6
        rows = reply["rows"]
        assert rows["idx"].shape == (2,)
        for f in REPLAY_FIELDS:
            assert rows[f].shape[0] == 2
        # dead shard answers SSTAT_DEAD, not silence
        shards[0].alive = False
        reply = _unpack_ssample_reply(shards[0].handle_ssample(
            _pack_ssample(0, shards[0].generation)))
        assert reply["status"] == SSTAT_DEAD

    def test_sprio_dispatch_applies_and_fences(self):
        plane, shards, reg = _plane(1, 16)
        for i in range(4):
            plane.feed(_tr(i))
        ok = shards[0].handle_sprio(_pack_sprio(
            0, shards[0].generation, np.array([0, 1], np.int32),
            np.array([2.0, 3.0], np.float32)))
        assert ok == {"status": "ok", "rows": 2}
        stale = shards[0].handle_sprio(_pack_sprio(
            0, shards[0].generation - 1, np.array([0], np.int32),
            np.array([9.0], np.float32)))
        assert stale["status"] == "stale"
        assert shards[0].stale_rejected == 1

    def test_malformed_frames_raise_connection_error(self):
        with pytest.raises(ConnectionError):
            _unpack_ssample(b"not a savez")
        with pytest.raises(ConnectionError):
            _unpack_ssample_reply(b"junk")
        plane, shards, _ = _plane(1, 8)
        with pytest.raises(ConnectionError):
            shards[0].handle_sprio(b"junk")

    def test_smass_dispatch(self):
        reg = ShardRegistry(ShardParams(shards=2, lease_s=30.0))
        grant = reg.handle_smass({"action": "acquire", "shard": 0,
                                  "incarnation": 1})
        assert grant["status"] == "ok"
        gen = grant["generation"]
        assert reg.handle_smass({"action": "renew", "shard": 0,
                                 "generation": gen,
                                 "report": {"mass": 2.5, "size": 3}}
                                )["status"] == "ok"
        st = reg.handle_smass({"action": "status"})
        assert st["shards"]["members"]["0"]["mass"] == 2.5
        assert reg.handle_smass({"action": "bogus", "shard": 0}
                                )["status"] == "error"
        assert reg.handle_smass({"action": "acquire", "shard": "x"}
                                )["status"] == "error"


class TestConfigPlane:
    def test_sharding_off_by_default(self):
        assert sharding_active() is False
        assert resolve_shard().shards == 0

    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_SHARD_SHARDS", "4")
        monkeypatch.setenv("TPU_APEX_SHARD_LEASE_S", "1.5")
        monkeypatch.setenv("TPU_APEX_SHARD_COORDINATOR", "h:9")
        sp = resolve_shard()
        assert (sp.shards, sp.lease_s, sp.coordinator) == (4, 1.5, "h:9")
        assert sharding_active() is True

    def test_status_block_counters_are_complete(self):
        plane, shards, reg = _plane(2, 32)
        sb = reg.status_block()
        assert set(sb["counters"]) == {
            "leases_granted", "leases_expired", "leases_released",
            "lease_fenced", "shard_lost_rows",
            "stale_writeback_rejected", "route_dropped", "rebalances",
            "joins_completed", "joins_timed_out"}
        assert sb["expected"] == 2 and sb["degraded"] is False


# ---------------------------------------------------------------------------
# the wire: gateway dispatch, remote channels, the disabled path
# ---------------------------------------------------------------------------

def _gateway(shards=None):
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock,
    )
    from pytorch_distributed_tpu.agents.param_store import ParamStore
    from pytorch_distributed_tpu.parallel.dcn import DcnGateway

    store = ParamStore(4)
    store.publish(np.zeros(4, np.float32))
    delivered = []
    gw = DcnGateway(store, GlobalClock(), ActorStats(),
                    put_chunk=lambda items: delivered.extend(items),
                    host="127.0.0.1", port=0, shards=shards)
    return gw, delivered


class TestWire:
    def test_noshard_status_code_pinned_to_dcn(self):
        # dcn authors exactly one shard frame (the no-handler reply);
        # this pin is what lets it avoid importing the plane
        from pytorch_distributed_tpu.memory.shard_plane import (
            SSTAT_NOSHARD,
        )
        from pytorch_distributed_tpu.parallel.dcn import (
            _pack_noshard_reply,
        )
        rep = _unpack_ssample_reply(_pack_noshard_reply())
        assert rep["status"] == SSTAT_NOSHARD

    def test_gateway_serves_shard_verbs_via_remote_channel(self):
        from pytorch_distributed_tpu.memory.shard_plane import (
            RemoteShardChannel,
        )

        plane, shards, reg = _plane(1, 16)
        for i in range(6):
            plane.feed(_tr(i, actor=i))
        gw, _ = _gateway(shards=shards[0])
        try:
            ch = RemoteShardChannel(("127.0.0.1", gw.port), 0,
                                    shards[0].generation)
            rep = ch.poll()
            assert rep is not None and rep["size"] == 6
            total = shards[0].per.sum_tree.total
            rows = ch.sample_rows(np.array([total * 0.2, total * 0.8]))
            assert rows is not None and rows["idx"].shape == (2,)
            for f in REPLAY_FIELDS:
                assert rows[f].shape[0] == 2
            # fenced write-back over the wire: wrong generation is a
            # counted reject, right generation applies
            assert ch.write_prio(rows["idx"], np.array([1.0, 2.0]),
                                 shards[0].generation - 1) is False
            assert shards[0].stale_rejected == 2
            assert ch.write_prio(rows["idx"], np.array([1.0, 2.0]),
                                 shards[0].generation) is True
            ch.close()
        finally:
            gw.close()

    def test_coordinator_gateway_serves_membership_and_status(self):
        from pytorch_distributed_tpu.memory.shard_plane import ShardLease

        reg = ShardRegistry(ShardParams(shards=2, lease_s=30.0))
        gw, _ = _gateway(shards=reg)
        try:
            lease = ShardLease(("127.0.0.1", gw.port), 0,
                               incarnation=1, capacity=8)
            grant = lease.acquire()
            assert grant["status"] == "ok" and lease.generation >= 1
            assert lease.renew({"mass": 1.5, "size": 2,
                                "ingested": 2}) is True
            from pytorch_distributed_tpu.parallel.dcn import fetch_status
            snap = fetch_status(("127.0.0.1", gw.port))
            assert snap["shards"]["members"]["0"]["ingested"] == 2
            assert snap["shards"]["degraded"] is True  # 1 of 2 up
            # the fleet_top panel renders straight off this STATUS
            import importlib
            fleet_top = importlib.import_module("tools.fleet_top")
            line = fleet_top.shards_line(snap) or ""
            assert line.startswith("  shards: 1/2 DEGRADED"), line
            assert fleet_top.shards_line({"slots": {}}) is None
            lease.release()
            assert reg.leases_released == 1
        finally:
            gw.close()

    def test_unsharded_gateway_zero_new_status_fields(self):
        from pytorch_distributed_tpu.memory.shard_plane import (
            SSTAT_NOSHARD, RemoteShardChannel,
        )

        gw, _ = _gateway(shards=None)
        try:
            from pytorch_distributed_tpu.parallel.dcn import fetch_status
            snap = fetch_status(("127.0.0.1", gw.port))
            assert "shards" not in snap
            # the verbs still answer (counted errors, never a crash)
            ch = RemoteShardChannel(("127.0.0.1", gw.port), 0, 1)
            rep = _unpack_ssample_reply(
                ch._rpc(__import__("pytorch_distributed_tpu.parallel.dcn",
                                   fromlist=["T_SSAMPLE"]).T_SSAMPLE,
                        _pack_ssample(0, 1)))
            assert rep["status"] == SSTAT_NOSHARD
            assert ch.poll() is None
            ch.close()
        finally:
            gw.close()


class TestFactory:
    def _opt(self):
        from pytorch_distributed_tpu.config import build_options
        return build_options(1, memory_type="prioritized",
                             env_type="fake")

    def test_disabled_builds_plain_per(self):
        from pytorch_distributed_tpu.factory import build_memory, probe_env
        opt = self._opt()
        handles = build_memory(opt, probe_env(opt))
        assert isinstance(handles.learner_side.memory, PrioritizedReplay)

    def test_enabled_builds_loopback_plane(self, monkeypatch):
        from pytorch_distributed_tpu.factory import build_memory, probe_env
        from pytorch_distributed_tpu.memory.shard_plane import (
            ShardedReplayPlane,
        )
        monkeypatch.setenv("TPU_APEX_SHARD_SHARDS", "2")
        opt = self._opt()
        handles = build_memory(opt, probe_env(opt))
        plane = handles.learner_side.memory
        assert isinstance(plane, ShardedReplayPlane)
        assert len(plane.channels) == 2
        # the QueueOwner boundary is intact: feeder -> drain -> sample
        # (rows must match the env spec or the validator quarantines)
        feeder = handles.actor_side
        for i in range(8):
            s = np.zeros(plane.state_shape, plane.state_dtype)
            feeder.feed(Transition(
                state0=s, action=plane.action_dtype.type(0),
                reward=np.float32(i), gamma_n=np.float32(0.99),
                state1=s, terminal1=np.float32(0.0),
                prov=make_prov(i, 0, 0, i)))
        feeder.flush()
        handles.learner_side.drain()
        assert handles.learner_side.size == 8
        b = handles.learner_side.sample(4, np.random.default_rng(0))
        assert b.index.shape == (4,)
