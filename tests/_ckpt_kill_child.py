"""Child process for the FAST kill-resume drill
(tests/test_checkpoint_epochs.py TestKillDrill): performs a sequence of
checkpoint-epoch saves of a small synthetic train state + replay, with
the ``CKPT_FAULTS`` env schedule (utils/faults.py, ``kill@FRAME``)
SIGKILLing the process at an exact write point — mid-Orbax-write,
between the state and replay writes, mid-manifest-commit
(utils/checkpoint.py ``_FRAME_POINTS``).

Run: python _ckpt_kill_child.py <model_name> <saves>
Prints ``COMMITTED <k> <step>`` after each surviving save and ``DONE``
if the schedule never fired."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    model_name, saves = sys.argv[1], int(sys.argv[2])

    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu.memory.shared_replay import SharedReplay
    from pytorch_distributed_tpu.utils import checkpoint as ckpt
    from pytorch_distributed_tpu.utils.experience import Transition

    mem = SharedReplay(capacity=64, state_shape=(4,), action_shape=(),
                       state_dtype=np.uint8, action_dtype=np.int32)
    rng = np.random.default_rng(0)
    step = 0
    for k in range(saves):
        for _ in range(8):
            mem.feed(Transition(
                state0=rng.integers(0, 255, (4,)).astype(np.uint8),
                action=np.int32(0), reward=np.float32(step),
                gamma_n=np.float32(0.99),
                state1=rng.integers(0, 255, (4,)).astype(np.uint8),
                terminal1=np.float32(0.0)))
        step += 10
        state = {"w": jnp.full((16,), float(step)), "step": jnp.int32(step)}
        ckpt.save_epoch(model_name, state=state, memory=mem,
                        extras={"learner_step": step,
                                "actor_step": step * 3},
                        retain=3)
        print(f"COMMITTED {k} {step}", flush=True)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
