"""HBM prioritized replay: sampling proportionality, IS weights, fused
write-back."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.memory.device_per import (
    DevicePerReplay, PerReplayState, per_sample, per_update_priorities,
)
from pytorch_distributed_tpu.utils.experience import Transition


def _mk(capacity=8, obs=(3,)):
    m = DevicePerReplay(capacity, obs, state_dtype=np.float32,
                        priority_exponent=1.0, importance_weight=0.5,
                        importance_anneal_steps=100)
    n = capacity // 2
    m.feed_chunk(Transition(
        state0=np.arange(n * 3, dtype=np.float32).reshape(n, 3),
        action=(np.arange(n) % 2).astype(np.int32),
        reward=np.arange(n, dtype=np.float32),
        gamma_n=np.full(n, 0.9, np.float32),
        state1=np.ones((n, 3), np.float32),
        terminal1=np.zeros(n, np.float32)))
    return m


def test_sampling_is_proportional_to_priority():
    m = _mk(capacity=8)
    # hand-set priorities: row 0 gets 10x the mass of rows 1-3
    m.state = m.state._replace(
        priority=jnp.asarray([10, 1, 1, 1, 0, 0, 0, 0], jnp.float32))
    b = m.sample(4096, jax.random.PRNGKey(0), beta=1.0)
    idx = np.asarray(b.index)
    assert idx.max() <= 3  # empty rows (priority 0) never drawn
    frac0 = (idx == 0).mean()
    np.testing.assert_allclose(frac0, 10 / 13, atol=0.03)


def test_is_weights_counteract_oversampling():
    m = _mk(capacity=8)
    m.state = m.state._replace(
        priority=jnp.asarray([10, 1, 1, 1, 0, 0, 0, 0], jnp.float32))
    b = m.sample(512, jax.random.PRNGKey(1), beta=1.0)
    w = np.asarray(b.weight)
    idx = np.asarray(b.index)
    # full correction at beta=1: weight ratio inverse to priority ratio,
    # normalised so the rarest row gets weight 1
    np.testing.assert_allclose(w[idx == 1], 1.0, rtol=1e-5)
    np.testing.assert_allclose(w[idx == 0], 0.1, rtol=1e-5)


def test_priority_writeback_and_max_tracking():
    m = _mk(capacity=8)
    s = per_update_priorities(m.state, jnp.asarray([0, 1]),
                              jnp.asarray([2.0, 0.5]), alpha=1.0)
    np.testing.assert_allclose(float(s.priority[0]), 2.0, atol=1e-5)
    np.testing.assert_allclose(float(s.priority[1]), 0.5, atol=1e-4)
    assert float(s.max_priority) >= 2.0
    # next feed enters at the new max
    s2 = s._replace()
    m.state = s2
    m.feed_chunk(Transition(
        state0=np.zeros((1, 3), np.float32), action=np.zeros(1, np.int32),
        reward=np.zeros(1, np.float32), gamma_n=np.ones(1, np.float32),
        state1=np.zeros((1, 3), np.float32),
        terminal1=np.zeros(1, np.float32)))
    i = (4) % 8  # cursor was at 4 after the initial half-fill
    np.testing.assert_allclose(float(m.state.priority[i]),
                               float(m.state.max_priority), rtol=1e-6)


def test_fused_step_trains_and_writes_back():
    from pytorch_distributed_tpu.models import DqnMlpModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )

    model = DqnMlpModel(action_space=2, hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)))
    tx = make_optimizer(1e-3)
    ts = init_train_state(params, tx)
    step = build_dqn_train_step(model.apply, tx)

    m = _mk(capacity=8)
    fused = m.build_fused_step(step, batch_size=4, donate=False)
    pr_before = np.asarray(m.state.priority).copy()
    ts2, rs2, metrics = fused(ts, m.state, jax.random.PRNGKey(2),
                              jnp.asarray(0.5, jnp.float32))
    assert int(ts2.step) == 1
    assert np.isfinite(float(metrics["learner/critic_loss"]))
    # sampled rows got |TD| priorities (almost surely != the initial max)
    assert not np.allclose(np.asarray(rs2.priority), pr_before)


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_multi_step_dispatch_per_topology(tmp_path):
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        1, memory_type="device-per", root_dir=str(tmp_path), num_actors=1,
        steps=60, learn_start=16, batch_size=16, memory_size=1024,
        actor_sync_freq=20, param_publish_freq=10, learner_freq=20,
        evaluator_freq=30, early_stop=60, steps_per_dispatch=4,
        visualize=False)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 60
