"""Observability plane drills: distributed trace propagation across a
real in-process gateway, flight-recorder dumps on SIGKILL (the
_blackbox_kill_child.py drill, same pattern as the checkpoint kill
drills), STATUS snapshot consistency under the chaos harness, histogram
percentile math, and smoke tests pinning tools/fleet_top.py and
tools/plot_run.py against generated run dirs so the tools cannot
silently rot."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.memory.feeder import QueueFeeder, QueueOwner
from pytorch_distributed_tpu.parallel.dcn import (
    DcnClient, DcnGateway, RemoteMemory, decode_chunk, encode_chunk,
    feed_queue_of, fetch_status,
)
from pytorch_distributed_tpu.utils import flight_recorder, tracing
from pytorch_distributed_tpu.utils.faults import FaultInjector, InjectedCrash
from pytorch_distributed_tpu.utils.metrics import (
    MetricsWriter, read_scalars, summarize_histogram,
)
from pytorch_distributed_tpu.utils.profiling import StepTimer
from tools.chaos_soak import SyntheticActor, tagged_transition

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_observability(monkeypatch):
    """Tracers/recorders are per-process registries; isolate each test —
    including the blackbox-dir env var an earlier in-process Topology.run
    exported for its spawn children."""
    monkeypatch.delenv("TPU_APEX_BLACKBOX_DIR", raising=False)
    tracing.reset()
    flight_recorder.reset()
    yield
    tracing.reset()
    flight_recorder.reset()


class _ListMemory:
    """Minimal single-owner memory for QueueOwner in trace drills."""

    capacity = 1 << 16

    def __init__(self):
        self.items = []

    def feed(self, transition, priority=None):
        self.items.append((transition, priority))

    @property
    def size(self):
        return len(self.items)


def _drain_until(owner, n, timeout=10.0):
    deadline = time.monotonic() + timeout
    while owner.size < n:
        assert time.monotonic() < deadline, \
            f"only {owner.size}/{n} transitions drained"
        owner.drain()
        time.sleep(0.01)


# ---------------------------------------------------------------------------
# histogram percentile math (utils/metrics.py satellite)
# ---------------------------------------------------------------------------

class TestHistogramMath:
    def test_nearest_rank_percentiles(self):
        s = summarize_histogram(list(range(1, 101)))  # 1..100
        assert s == {"count": 100, "mean": 50.5,
                     "p50": 50, "p95": 95, "max": 100}

    def test_order_invariant_and_small_samples(self):
        assert summarize_histogram([9.0]) == {
            "count": 1, "mean": 9.0, "p50": 9.0, "p95": 9.0, "max": 9.0}
        a = summarize_histogram([3.0, 1.0, 2.0])
        assert (a["p50"], a["p95"], a["max"]) == (2.0, 3.0, 3.0)
        with pytest.raises(ValueError):
            summarize_histogram([])

    def test_writer_emits_stamped_histogram_row(self, tmp_path):
        w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                          role="learner", run_id="run-7")
        w.histogram("trace/learner/learn_ms", [1.0, 2.0, 100.0], step=42)
        w.scalar("learner/critic_loss", 0.5, step=42)
        w.close()
        rows = read_scalars(str(tmp_path))
        hist = [r for r in rows if r.get("kind") == "histogram"]
        assert len(hist) == 1
        h = hist[0]
        assert h["p50"] == 2.0 and h["max"] == 100.0 and h["count"] == 3
        # every row — scalar and histogram alike — carries role + run_id
        for r in rows:
            assert r["role"] == "learner" and r["run_id"] == "run-7"


class TestTornJsonl:
    def test_read_scalars_skips_torn_trailing_line(self, tmp_path):
        path = tmp_path / "scalars.jsonl"
        good = [{"tag": "a", "value": 1.0, "step": 1, "wall": 2.0},
                {"tag": "b", "value": 2.0, "step": 2, "wall": 3.0}]
        with open(path, "w") as f:
            for r in good:
                f.write(json.dumps(r) + "\n")
            # SIGKILL mid-write: a partial JSON object, no newline
            f.write('{"tag": "c", "val')
        rows = read_scalars(str(tmp_path))
        assert rows == good  # torn tail skipped, never raised


class TestStepTimer:
    def test_drain_reports_mean_max_and_calls(self):
        t = StepTimer("x")
        for pause in (0.001, 0.02):
            with t.phase("p"):
                time.sleep(pause)
        out = t.drain()
        assert out["x/time_p_calls"] == 2.0
        # the stall is visible in max, averaged down in mean
        assert out["x/time_p_max_ms"] >= 20.0 * 0.5  # timer slop margin
        assert out["x/time_p_max_ms"] >= out["x/time_p_ms"]
        assert t.drain() == {}  # drain resets everything, max included


# ---------------------------------------------------------------------------
# distributed trace propagation (tentpole part 1)
# ---------------------------------------------------------------------------

class TestTracePropagation:
    def test_chunk_trace_survives_the_wire_encoding(self):
        chunk = tracing.TracedChunk(
            [(tagged_transition(5), 0.5)], trace_id=1234, born=99.5)
        out = decode_chunk(encode_chunk(chunk))
        assert isinstance(out, tracing.TracedChunk)
        assert out.trace_id == 1234 and out.born == 99.5
        # plain lists stay plain: wire format is backward compatible
        assert not isinstance(
            decode_chunk(encode_chunk([(tagged_transition(5), None)])),
            tracing.TracedChunk)

    def test_end_to_end_trace_across_real_gateway(self, tmp_path):
        """The acceptance chain: an actor-side feeder mints a trace id,
        the id rides the DCN wire and the learner-side spawn queue, and
        the enqueue/gateway/feed/learn spans all land in the metrics
        stream sharing that id, with histogram percentiles."""
        clock, stats = GlobalClock(), ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        owner = QueueOwner(_ListMemory())
        handles = types.SimpleNamespace(learner_side=owner)
        gw = DcnGateway(store, clock, stats,
                        put_chunk=feed_queue_of(handles),
                        host="127.0.0.1", port=0)
        client = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                           heartbeat_interval=0)
        try:
            memory = RemoteMemory(client, chunk=4)
            memory.set_tracer(tracing.get_tracer("actor"))
            for i in range(4):
                memory.feed(tagged_transition(i), None)  # flushes at 4
            _drain_until(owner, 4)
            # learner tail: sample/learn attach to the drained trace
            with tracing.get_tracer("learner").span(
                    "learn", trace_id=tracing.current_trace()):
                pass
            writer = MetricsWriter(str(tmp_path),
                                   enable_tensorboard=False,
                                   role="learner", run_id="trace-run")
            for role in ("actor", "gateway", "feeder", "learner"):
                tracing.get_tracer(role).flush_to(writer, step=7)
            writer.close()
        finally:
            client.close()
            gw.close()

        rows = read_scalars(str(tmp_path))
        spans = {r["span"]: r for r in rows if r.get("kind") == "span"}
        assert set(spans) >= {"enqueue", "gateway", "feed", "learn"}
        tids = {r["trace_id"] for r in spans.values()}
        assert len(tids) == 1  # ONE end-to-end trace id across all hops
        assert spans["enqueue"]["role"] == "actor"
        assert spans["gateway"]["role"] == "gateway"
        assert spans["feed"]["role"] == "feeder"
        assert spans["learn"]["role"] == "learner"
        hists = {r["tag"]: r for r in rows if r.get("kind") == "histogram"}
        for tag in ("trace/actor/enqueue_ms", "trace/gateway/gateway_ms",
                    "trace/feeder/feed_ms", "trace/learner/learn_ms"):
            assert tag in hists
            assert hists[tag]["p95"] >= hists[tag]["p50"] >= 0.0
            assert hists[tag]["max"] >= hists[tag]["p95"]

    def test_local_queue_path_traces_without_dcn(self):
        """Single-host topologies trace too: the spawn-queue hop records
        enqueue + feed spans with one id, no gateway involved."""
        owner = QueueOwner(_ListMemory())
        feeder = owner.make_feeder(chunk=2)
        feeder.set_tracer(tracing.get_tracer("actor"))
        feeder.feed(tagged_transition(0), None)
        feeder.feed(tagged_transition(1), None)
        _drain_until(owner, 2)
        a_hist, a_rows, a_counts = tracing.get_tracer("actor").drain()
        f_hist, f_rows, _f_counts = tracing.get_tracer("feeder").drain()
        assert "enqueue" in a_hist and "feed" in f_hist
        assert a_counts["enqueue"] == 1
        assert a_rows[0]["trace_id"] == f_rows[0]["trace_id"]

    def test_trace_kill_switch_ships_plain_lists(self, monkeypatch):
        """TPU_APEX_TRACE=0 removes the whole per-chunk cost: no trace
        id minted, nothing for downstream hops to record."""
        monkeypatch.setenv("TPU_APEX_TRACE", "0")
        owner = QueueOwner(_ListMemory())
        feeder = owner.make_feeder(chunk=1)
        feeder.set_tracer(tracing.get_tracer("actor"))
        feeder.feed(tagged_transition(0), None)
        _drain_until(owner, 1)
        hist, _rows, _counts = tracing.get_tracer("feeder").drain()
        assert hist == {}  # no TracedChunk ever crossed the queue

    def test_tracer_disabled_records_nothing(self):
        t = tracing.Tracer("off-role", enabled=False)
        t.record("x", 1.0, trace_id=5)
        with t.span("y", trace_id=6):
            pass
        hist, rows, counts = t.drain()
        assert hist == {} and rows == [] and counts == {}

    def test_sampling_thins_rows_but_not_histograms(self):
        t = tracing.Tracer("sampled", enabled=True, sample=0.1)
        for i in range(100):
            t.record("s", 1.0, trace_id=i + 1)
        hist, rows, counts = t.drain()
        assert len(hist["s"]) == 100      # histograms see every event
        assert counts["s"] == 100
        assert 5 <= len(rows) <= 15       # rows are 1-in-10 sampled

    def test_reservoir_keeps_true_count_and_samples_the_tail(self):
        """Past MAX_SAMPLES the reservoir keeps an equal-probability
        sample of the WHOLE window (a late stall can still reach the
        percentiles) and the drained count reports every event."""
        t = tracing.Tracer("busy", enabled=True, sample=0.0)
        t.MAX_SAMPLES = 64
        for _ in range(1000):
            t.record("s", 1.0)
        for _ in range(1000):  # the late half of the window
            t.record("s", 9.0)
        hist, _rows, counts = t.drain()
        assert counts["s"] == 2000
        assert len(hist["s"]) == 64
        assert 9.0 in hist["s"]  # P(no late sample) = 0.5^64 — never


# ---------------------------------------------------------------------------
# flight recorder (tentpole part 2)
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded_and_dump_is_digestible(self, tmp_path):
        rec = flight_recorder.FlightRecorder("actor-3", capacity=16)
        for i in range(100):
            rec.record("tick", i=i)
        path = rec.dump(log_dir=str(tmp_path), reason="unit")
        assert path is not None and path.endswith("blackbox/actor-3.jsonl")
        with open(path) as f:
            lines = [json.loads(line) for line in f]  # every line parses
        header, events = lines[0], lines[1:]
        assert header["kind"] == "dump" and header["reason"] == "unit"
        assert header["recorded_total"] == 100
        assert len(events) == 16  # the ring kept only the newest tail
        assert [e["i"] for e in events] == list(range(84, 100))

    def test_unconfigured_process_never_writes(self, tmp_path):
        rec = flight_recorder.get_recorder("quiet")
        rec.record("tick")
        assert rec.dump(reason="no dir") is None
        assert flight_recorder.dump_all("no dir") == []
        assert not (tmp_path / "blackbox").exists()

    def test_dump_on_sigkill_drill(self, tmp_path):
        """The _ckpt_kill_child.py pattern aimed at the blackbox: the
        child is SIGKILLed by a scripted fault at frame 37 and must still
        leave a digestible post-mortem (the injector dumps pre-signal —
        nothing can run after SIGKILL)."""
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tests", "_blackbox_kill_child.py"),
             str(tmp_path), "kill@37"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == -signal.SIGKILL
        assert "DONE" not in proc.stdout  # the drill really fired
        path = tmp_path / "blackbox" / "actor-0.jsonl"
        assert path.exists()
        with open(path) as f:
            lines = [json.loads(line) for line in f]
        assert lines[0]["kind"] == "dump"
        assert "kill" in lines[0]["reason"]
        ticks = [e["i"] for e in lines if e["kind"] == "tick"]
        assert ticks and ticks[-1] == 37  # events up to the kill point
        # the injector's own ring recorded the fatal fault
        faults_path = tmp_path / "blackbox" / "faults-blackbox-drill.jsonl"
        assert faults_path.exists()
        with open(faults_path) as f:
            fault_lines = [json.loads(line) for line in f]
        assert any(e.get("action") == "kill" for e in fault_lines)


# ---------------------------------------------------------------------------
# STATUS verb / live health plane (tentpole part 3)
# ---------------------------------------------------------------------------

class TestStatusPlane:
    def _plane(self, **gw_kwargs):
        clock, stats = GlobalClock(), ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        gw = DcnGateway(store, clock, stats,
                        put_chunk=lambda items: None,
                        host="127.0.0.1", port=0, **gw_kwargs)
        return gw, clock

    def test_status_is_sessionless_and_carries_health_fields(self):
        gw, clock = self._plane(
            health=lambda: {"replay_size": 7, "replay_capacity": 10})
        try:
            clock.set_learner_step(123)
            status = fetch_status(("127.0.0.1", gw.port))
            assert status["learner_step"] == 123
            assert status["slots"] == {}
            assert status["replay_size"] == 7
            assert status["replay_capacity"] == 10
            assert status["uptime"] >= 0
            assert gw.active_slots == {}  # the probe claimed no slot
        finally:
            gw.close()

    def test_health_provider_errors_degrade_not_crash(self):
        def bad_health():
            raise RuntimeError("replay not attached yet")

        gw, _clock = self._plane(health=bad_health)
        try:
            status = fetch_status(("127.0.0.1", gw.port))
            assert "health_error" in status
            assert status["slots"] == {}  # core snapshot still served
        finally:
            gw.close()

    def test_status_consistency_under_chaos(self):
        """The chaos-harness consistency drill: a flowing fleet's STATUS
        matches the gateway's own registry; after one role dies its slot
        leaves the snapshot while the survivor keeps flowing."""
        gw, clock = self._plane(idle_deadline=1.0)
        fleet = [SyntheticActor(("127.0.0.1", gw.port), slot=i, pace=0.002,
                                client_kwargs=dict(heartbeat_interval=0.2,
                                                   reconnect_timeout=5.0)
                                ).start()
                 for i in range(2)]
        try:
            deadline = time.monotonic() + 10
            while set(gw.active_slots) != {0, 1}:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            status = fetch_status(("127.0.0.1", gw.port))
            # snapshot agrees with the registry, slot for slot
            assert {int(s) for s in status["slots"]} == {0, 1}
            for slot, info in status["slots"].items():
                assert info["incarnation"] == gw.active_slots[int(slot)]
                assert 0.0 <= info["heartbeat_age"] < 5.0
            # one role dies (loop stopped, socket torn like a process
            # death): its slot must leave the snapshot, the survivor stays
            dead = fleet[0]
            dead.client.stop.set()  # ends its loop (clean close follows)
            dead.thread.join(10)
            assert not dead.thread.is_alive()
            deadline = time.monotonic() + 15
            while 0 in gw.active_slots:  # BYE'd or idle-reaped at 1 s
                assert time.monotonic() < deadline
                time.sleep(0.05)
            status = fetch_status(("127.0.0.1", gw.port))
            assert list(status["slots"]) == ["1"]
            assert status["chunks_in"] > 0
        finally:
            clock.stop.set()
            for a in fleet:
                a.thread.join(10)
            gw.close()


# ---------------------------------------------------------------------------
# the acceptance drill: chaos kill → STATUS + blackbox + e2e trace
# ---------------------------------------------------------------------------

class TestChaosKillAcceptance:
    def test_killed_slot_leaves_status_blackbox_and_trace(self, tmp_path):
        """ISSUE 3 acceptance: a fast-tier chaos drill kills one slot;
        the surviving gateway answers STATUS consistently, the killed
        role leaves a digestible blackbox dump, and one end-to-end trace
        (actor→gateway→feeder→learner sharing an id) lands in the
        metrics stream with histogram percentiles."""
        flight_recorder.configure(str(tmp_path))
        clock, stats = GlobalClock(), ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        owner = QueueOwner(_ListMemory())
        handles = types.SimpleNamespace(learner_side=owner)
        gw = DcnGateway(store, clock, stats,
                        put_chunk=feed_queue_of(handles),
                        host="127.0.0.1", port=0,
                        health=lambda: {"replay_size": owner.size})

        # the doomed role: an InjectedCrash fault kills its loop (the
        # whole-process SIGKILL variant is TestFlightRecorder's drill)
        doomed = DcnClient(("127.0.0.1", gw.port), process_ind=1,
                           heartbeat_interval=0,
                           faults=FaultInjector.scripted("crash@3",
                                                         name="drill"))
        doomed_rec = flight_recorder.get_recorder("actor-1")
        doomed_rec.record("session-start")
        # the surviving role: a real traced feeder
        survivor = DcnClient(("127.0.0.1", gw.port), process_ind=0,
                             heartbeat_interval=0)
        try:
            memory = RemoteMemory(survivor, chunk=4)
            memory.set_tracer(tracing.get_tracer("actor"))
            for i in range(4):
                memory.feed(tagged_transition(i), None)
            _drain_until(owner, 4)
            with tracing.get_tracer("learner").span(
                    "learn", trace_id=tracing.current_trace()):
                pass

            with pytest.raises(InjectedCrash):
                for _ in range(8):  # frame 3 of the doomed client dies
                    doomed.tick(actor_steps=1)
            doomed_rec.record("crash", error="InjectedCrash")
            flight_recorder.dump_all("actor-1 crashed (chaos drill)")
            # a dead process's sockets close from the OS side; simulate
            # that so the gateway frees the slot now, not at idle-reap
            doomed._sock.close()

            # 1) the surviving gateway answers STATUS consistently
            deadline = time.monotonic() + 10
            while 1 in gw.active_slots:  # the dead conn releases its slot
                assert time.monotonic() < deadline
                time.sleep(0.02)
            status = fetch_status(("127.0.0.1", gw.port))
            assert list(status["slots"]) == ["0"]
            assert (status["slots"]["0"]["incarnation"]
                    == gw.active_slots[0])
            assert status["replay_size"] == 4
            assert status["chunks_in"] >= 1

            # 2) the killed role left a digestible blackbox dump
            path = tmp_path / "blackbox" / "actor-1.jsonl"
            assert path.exists()
            with open(path) as f:
                lines = [json.loads(line) for line in f]
            assert lines[0]["kind"] == "dump"
            kinds = {e["kind"] for e in lines[1:]}
            assert {"session-start", "crash"} <= kinds
            # the drill's injector fingerprinted itself too
            assert (tmp_path / "blackbox" / "faults-drill.jsonl").exists()

            # 3) one end-to-end trace with histogram percentiles
            writer = MetricsWriter(str(tmp_path),
                                   enable_tensorboard=False,
                                   role="learner", run_id="chaos-run")
            for role in ("actor", "gateway", "feeder", "learner"):
                tracing.get_tracer(role).flush_to(writer, step=1)
            writer.close()
            rows = read_scalars(str(tmp_path))
            spans = [r for r in rows if r.get("kind") == "span"]
            by_span = {r["span"]: r["trace_id"] for r in spans}
            assert set(by_span) >= {"enqueue", "gateway", "feed", "learn"}
            assert len({by_span[s] for s in
                        ("enqueue", "gateway", "feed", "learn")}) == 1
            hist_tags = {r["tag"] for r in rows
                         if r.get("kind") == "histogram"}
            assert {"trace/actor/enqueue_ms", "trace/gateway/gateway_ms",
                    "trace/feeder/feed_ms",
                    "trace/learner/learn_ms"} <= hist_tags
        finally:
            survivor.close()
            doomed.close()
            gw.close()


# ---------------------------------------------------------------------------
# CI/tooling smoke: the observability tools against generated run dirs
# ---------------------------------------------------------------------------

class TestToolsSmoke:
    def test_fleet_top_json_against_live_gateway(self):
        clock, stats = GlobalClock(), ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        gw = DcnGateway(store, clock, stats,
                        put_chunk=lambda items: None,
                        host="127.0.0.1", port=0,
                        health=lambda: {"replay_size": 3})
        try:
            clock.set_learner_step(17)
            proc = subprocess.run(
                [sys.executable, os.path.join(_REPO, "tools",
                                              "fleet_top.py"),
                 f"127.0.0.1:{gw.port}", "--json"],
                capture_output=True, text=True, timeout=60,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stderr
            status = json.loads(proc.stdout)
            assert status["learner_step"] == 17
            assert status["replay_size"] == 3
        finally:
            gw.close()

    def test_fleet_top_json_unreachable_gateway_exits_nonzero(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "fleet_top.py"),
             "127.0.0.1:1", "--json", "--timeout", "2"],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 1
        assert "unreachable" in proc.stderr

    def test_plot_run_against_generated_run_dir(self, tmp_path):
        pytest.importorskip("matplotlib")
        writer = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                               role="logger", run_id="smoke")
        wall = time.time()
        for step in range(5):
            writer.scalars({"evaluator/avg_reward": step * 1.0,
                            "learner/critic_loss": 1.0 / (step + 1),
                            "actor/total_nframes": step * 100.0},
                           step=step, wall=wall + step)
        # non-scalar rows must not break the plotter
        writer.histogram("trace/learner/learn_ms", [1.0, 2.0], step=4)
        writer.span("learn", role="learner", trace_id="ab" * 8,
                    dur_ms=1.5, step=4)
        writer.close()
        out = tmp_path / "smoke.png"
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "plot_run.py"),
             str(tmp_path), "--out", str(out)],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "MPLBACKEND": "Agg"})
        assert proc.returncode == 0, proc.stderr
        assert out.exists() and out.stat().st_size > 0
