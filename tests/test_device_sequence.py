"""HBM segment ring (memory/device_sequence.py): ring semantics,
proportional sampling, fused burn-in/train/write-back, ingest, resume."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.memory.device_sequence import (
    DeviceSequenceIngest, DeviceSequenceReplay, SegmentChunk,
    seq_update_priorities,
)
from pytorch_distributed_tpu.memory.sequence_replay import Segment

T, S, L = 4, (3,), 4  # seq_len, state_shape, lstm_dim


def _seg(v: float) -> Segment:
    return Segment(
        obs=np.full((T + 1, *S), v, np.float32),
        action=(np.arange(T) % 2).astype(np.int32),
        reward=np.full(T, v, np.float32),
        terminal=np.zeros(T, np.float32),
        mask=np.ones(T, np.float32),
        c0=np.full(L, v, np.float32),
        h0=np.full(L, -v, np.float32))


def _chunk(vals) -> SegmentChunk:
    segs = [_seg(float(v)) for v in vals]
    return SegmentChunk(*(np.stack([getattr(s, f) for s in segs])
                          for f in SegmentChunk._fields))


def _mk(capacity=8, alpha=1.0):
    m = DeviceSequenceReplay(capacity, T, S, L, state_dtype=np.float32,
                             priority_exponent=alpha,
                             importance_weight=0.5,
                             importance_anneal_steps=100)
    m.feed_chunk(_chunk(range(4)))
    return m


def test_ring_write_wraps_and_tracks_fill():
    m = _mk(capacity=8)
    assert m.size == 4 and int(m.state.pos) == 4
    m.feed_chunk(_chunk(range(4, 10)))  # 6 more: wraps past capacity
    assert m.size == 8 and int(m.state.pos) == 2
    # rows 8, 9 overwrote slots 0, 1; row 2 survives
    np.testing.assert_allclose(np.asarray(m.state.reward)[0], 8.0)
    np.testing.assert_allclose(np.asarray(m.state.reward)[1], 9.0)
    np.testing.assert_allclose(np.asarray(m.state.reward)[2], 2.0)


def test_sampling_proportional_and_skips_empty():
    m = _mk(capacity=8)
    m.state = m.state._replace(
        priority=jnp.asarray([10, 1, 1, 1, 0, 0, 0, 0], jnp.float32))
    b = m.sample(4096, jax.random.PRNGKey(0), beta=1.0)
    idx = np.asarray(b.index)
    assert idx.max() <= 3  # empty rows never drawn
    np.testing.assert_allclose((idx == 0).mean(), 10 / 13, atol=0.03)
    # IS weights at beta=1 fully counteract: rarest row normalised to 1
    w = np.asarray(b.weight)
    np.testing.assert_allclose(w[idx == 1], 1.0, rtol=1e-5)
    np.testing.assert_allclose(w[idx == 0], 0.1, rtol=1e-5)
    # sampled segment fields gather the right rows
    np.testing.assert_allclose(np.asarray(b.reward)[idx == 2][:, 0], 2.0)
    np.testing.assert_allclose(np.asarray(b.c0)[idx == 3][:, 0], 3.0)


def test_writeback_then_new_rows_enter_at_max():
    m = _mk(capacity=8)
    m.state = seq_update_priorities(m.state, jnp.asarray([0, 1]),
                                    jnp.asarray([2.0, 0.5]), alpha=1.0)
    np.testing.assert_allclose(float(m.state.priority[0]), 2.0, atol=1e-5)
    assert float(m.state.max_priority) >= 2.0
    m.feed_chunk(_chunk([42]))  # lands at slot 4
    np.testing.assert_allclose(float(m.state.priority[4]),
                               float(m.state.max_priority), rtol=1e-6)


def _drqn_setup(lstm=8):
    from pytorch_distributed_tpu.models.drqn import DrqnMlpModel
    from pytorch_distributed_tpu.ops.losses import (
        init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.ops.sequence_losses import (
        build_drqn_train_step,
    )

    model = DrqnMlpModel(action_space=2, hidden_dim=16, lstm_dim=L)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, *S)))
    tx = make_optimizer(1e-3)
    ts = init_train_state(params, tx)
    step = build_drqn_train_step(model.apply, tx, burn_in=1, nstep=2,
                                 target_model_update=100)
    return ts, step


def test_fused_step_trains_and_writes_back():
    ts, step = _drqn_setup()
    m = _mk(capacity=8)
    fused = m.build_fused_step(step, batch_size=4, donate=False)
    pr_before = np.asarray(m.state.priority).copy()
    ts2, rs2, metrics = fused(ts, m.state, jax.random.PRNGKey(2),
                              jnp.asarray(0.5, jnp.float32))
    assert int(ts2.step) == 1
    assert np.isfinite(float(metrics["learner/critic_loss"]))
    assert not np.allclose(np.asarray(rs2.priority), pr_before)


def test_fused_multi_step_scans_k_updates():
    ts, step = _drqn_setup()
    m = _mk(capacity=8)
    K = 3
    fused = m.build_fused_step(step, batch_size=4, donate=False,
                               steps_per_call=K)
    keys = jax.random.split(jax.random.PRNGKey(3), K)
    ts2, rs2, metrics = fused(ts, m.state, keys,
                              jnp.asarray(0.5, jnp.float32))
    assert int(ts2.step) == K
    assert np.isfinite(float(metrics["learner/critic_loss"]))


def test_snapshot_restore_roundtrip():
    m = _mk(capacity=8)
    m.feed_chunk(_chunk(range(4, 10)))  # wrapped ring: age-order matters
    m.state = seq_update_priorities(m.state, jnp.asarray([2, 3]),
                                    jnp.asarray([7.0, 3.0]), alpha=1.0)
    snap = m.snapshot()
    assert snap["reward"].shape[0] == 8
    # oldest-first: the wrapped ring's oldest surviving row is 2
    np.testing.assert_allclose(snap["reward"][0, 0], 2.0)

    m2 = DeviceSequenceReplay(8, T, S, L, state_dtype=np.float32,
                              priority_exponent=1.0)
    assert m2.restore(snap) == 8
    # restore re-bases the ring at slot 0; AGE-ordered contents (a second
    # snapshot) must match the original exactly, leaves included
    snap2 = m2.snapshot()
    for k, v in snap.items():
        np.testing.assert_allclose(np.asarray(snap2[k]), np.asarray(v),
                                   rtol=1e-6, err_msg=k)


def test_ingest_drains_feeder_chunks():
    ing = DeviceSequenceIngest(16, T, S, L, state_dtype=np.float32,
                               chunk_size=4)
    feeder = ing.make_feeder(chunk=2)
    ing.attach(mesh=None)
    for i in range(9):
        feeder.feed(_seg(float(i)), None)
    feeder.flush()
    # mp.Queue's feeder thread makes puts visible asynchronously; drain
    # until the data lands (the learner loop drains every step anyway)
    import time

    deadline = time.monotonic() + 5.0
    while (ing.size + len(ing._pending) < 9
           and time.monotonic() < deadline):
        ing.drain()
        time.sleep(0.01)
    # 9 segments: two chunks of 4 land, 1 stays pending below chunk_size
    assert ing.size == 8
    snap = ing.snapshot()  # snapshot flushes the remainder
    assert snap["reward"].shape[0] == 9
    np.testing.assert_allclose(snap["c0"][:, 0], np.arange(9.0))
    ing.close()


def test_packed_ring_shape_matches_builder_format():
    # frame-packed pixel rows: (T+C, H, W) — the SegmentBuilder wire format
    m = DeviceSequenceReplay(4, 6, (4, 8, 8), 8, state_dtype=np.uint8,
                             pack_frames=4)
    assert m.state.obs.shape == (4, 10, 8, 8)


@pytest.mark.slow
@pytest.mark.timeout(1200)
def test_device_sequence_chain_topology_learns(tmp_path):
    """The config-13 chain R2D2 bar, on the HBM segment ring: the fused
    sample->train->write-back plane must LEARN, not just run."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        13, memory_type="device-sequence", root_dir=str(tmp_path),
        num_actors=2, steps=1200, learn_start=8, batch_size=16,
        memory_size=4096, seq_len=16, seq_overlap=8, burn_in=4, nstep=3,
        actor_sync_freq=20, param_publish_freq=5, learner_freq=50,
        evaluator_freq=1, max_replay_ratio=64.0, lr=2e-3,
        target_model_update=100, steps_per_dispatch=4)
    runtime.train(opt, backend="thread")
    opt2 = build_options(13, root_dir=str(tmp_path), mode=2,
                         tester_nepisodes=5, seq_len=16,
                         model_file=opt.model_name)
    out = runtime.test(opt2)
    assert out["avg_reward"] >= 0.9
    assert out["avg_steps"] <= 10
