import numpy as np

from pytorch_distributed_tpu.utils.random_process import OrnsteinUhlenbeckProcess


def test_ou_mean_reversion():
    p = OrnsteinUhlenbeckProcess(size=1, theta=0.5, mu=2.0, sigma=0.0, seed=0)
    p.x_prev = np.array([0.0])
    for _ in range(50):
        x = p.sample()
    assert abs(x[0] - 2.0) < 0.01


def test_ou_sigma_anneal():
    p = OrnsteinUhlenbeckProcess(size=1, sigma=1.0, sigma_min=0.1,
                                 n_steps_annealing=10, seed=0)
    for _ in range(20):
        p.sample()
    assert p.current_sigma == 0.1


def test_ou_deterministic_given_seed():
    a = OrnsteinUhlenbeckProcess(size=3, seed=42)
    b = OrnsteinUhlenbeckProcess(size=3, seed=42)
    for _ in range(5):
        np.testing.assert_array_equal(a.sample(), b.sample())


def test_ou_statistics():
    # stationary std of OU: sigma * sqrt(dt) / sqrt(2 theta dt) approx
    p = OrnsteinUhlenbeckProcess(size=10000, theta=0.15, sigma=0.3, seed=7)
    for _ in range(200):
        x = p.sample()
    assert abs(np.mean(x)) < 0.05
    assert 0.3 < np.std(x) < 0.8
