"""Actor hot-loop pipeline (ISSUE 4): schedule equivalence + plumbing.

The contract under test: ``actor_backend`` changes WHEN work happens,
never WHAT is computed.  ``pipelined`` (the default) dispatches tick
k+1's fused act while the host feeds tick k; ``batched`` moves the
forward to a shared InferenceServer; ``inline`` is the serial reference.
All three must produce bit-identical action/transition streams under a
fixed seed, because per-tick randomness is a pure function of
(actor, tick, env row) — models/policies.tick_keys — and the weight-sync
point is schedule-invariant (agents/actor._drive_actor_loop docstring).

Everything here runs in-process on CPU via
``agents.actor.bounded_actor_run`` (one fixed published param snapshot, a
recording sink, a tick-bounded clock) — fast tier, no spawns.
"""

from __future__ import annotations

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.agents.actor import bounded_actor_run


def _opt(cfg, tmp_path, backend, **kw):
    kw.setdefault("num_actors", 2)
    kw.setdefault("num_envs_per_actor", 3)
    # no mid-run flush: leaves the StepTimer intact for phase asserts
    kw.setdefault("actor_freq", 10 ** 9)
    return build_options(cfg, root_dir=str(tmp_path), refs=f"t_{backend}",
                         actor_backend=backend, visualize=False, **kw)


def _assert_streams_equal(a, b):
    assert len(a) == len(b), (len(a), len(b))
    for (t1, p1), (t2, p2) in zip(a, b):
        assert type(t1) is type(t2)
        for f in t1._fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(t1, f)), np.asarray(getattr(t2, f)),
                err_msg=f"field {f}")
        if p1 is None or p2 is None:
            assert p1 is None and p2 is None
        else:
            assert p1 == p2


# ---------------------------------------------------------------------------
# determinism: pipelined == inline, bit for bit
# ---------------------------------------------------------------------------


def test_pipelined_matches_inline_dqn(tmp_path):
    runs = {b: bounded_actor_run(_opt(1, tmp_path, b), 60)
            for b in ("inline", "pipelined")}
    assert runs["inline"]["stream"], "no transitions collected"
    _assert_streams_equal(runs["inline"]["stream"],
                          runs["pipelined"]["stream"])


def test_pipelined_matches_inline_dqn_per_priorities(tmp_path):
    """With PER on, the actor-computed initial priorities ride the
    stream too — the q_sel/q_max alignment across the one-tick holding
    pen must survive the reordered schedule."""
    runs = {b: bounded_actor_run(
        _opt(1, tmp_path, b, memory_type="prioritized"), 60)
        for b in ("inline", "pipelined")}
    priorities = [p for _, p in runs["inline"]["stream"]]
    assert any(p is not None for p in priorities)
    _assert_streams_equal(runs["inline"]["stream"],
                          runs["pipelined"]["stream"])


def test_pipelined_matches_inline_ddpg(tmp_path):
    """OU noise is sampled host-side at collect time in BOTH schedules,
    so the noise stream — and with it every continuous action — lines
    up."""
    runs = {b: bounded_actor_run(_opt(2, tmp_path, b), 50)
            for b in ("inline", "pipelined")}
    assert runs["inline"]["stream"]
    _assert_streams_equal(runs["inline"]["stream"],
                          runs["pipelined"]["stream"])


@pytest.mark.parametrize("cfg", [13, 15], ids=["drqn-lstm", "dtqn"])
def test_pipelined_matches_inline_recurrent(tmp_path, cfg):
    """Recurrent actors: the pipelined loop keeps the carry
    device-resident and resets rows via the fused act's reset mask; the
    serial loop drives the same engine.  Segment streams — including the
    stored carry_before rows around episode resets — must match
    exactly."""
    # eps=1.0: fully random actions — a random walk is what actually
    # reaches the chain's terminal under untrained weights, and episode
    # ends are the point of this test (carry resets).  Seeded, so the
    # terminal hits reproduce exactly.
    kw = dict(seq_len=8, seq_overlap=4, eps=1.0)
    runs = {b: bounded_actor_run(_opt(cfg, tmp_path, b, **kw), 120)
            for b in ("inline", "pipelined")}
    segs = runs["inline"]["stream"]
    assert segs, "no segments collected"
    # the chain env terminates inside 60 ticks: carry resets were hit
    assert any(np.asarray(s.terminal).any() for s, _ in segs)
    _assert_streams_equal(segs, runs["pipelined"]["stream"])


# ---------------------------------------------------------------------------
# overlap smoke: the async schedule never reorders advance vs env resets
# ---------------------------------------------------------------------------


def test_pipelined_no_reorder_against_env_resets(tmp_path):
    """With nstep=1 every transition is a raw (s, a, s') edge: walking
    one env's stream, state0 must chain from the previous transition's
    state1 — except across a terminal, where it must chain from the
    RESET observation.  A pipelined loop that fed tick k after
    dispatching on tick k+1's post-reset obs out of order would break
    the chain."""
    # eps=1.0: random-walk actions so the chain terminal is actually hit
    # (greedy under untrained weights may never reach it); seeded.
    opt = _opt(1, tmp_path, "pipelined", num_envs_per_actor=1, nstep=1,
               eps=1.0)
    stream = bounded_actor_run(opt, 250)["stream"]
    assert stream
    reset_obs = np.zeros(8, np.float32)
    reset_obs[0] = 1.0
    terminals = 0
    prev = None
    for t, _p in stream:
        if prev is not None:
            if prev.terminal1:
                np.testing.assert_array_equal(np.asarray(t.state0),
                                              reset_obs)
                terminals += 1
            else:
                np.testing.assert_array_equal(np.asarray(t.state0),
                                              np.asarray(prev.state1))
        prev = t
    assert terminals >= 1, "no episode reset inside the window"


# ---------------------------------------------------------------------------
# CI throughput smoke: overlap exists, and nothing retraces per tick
# ---------------------------------------------------------------------------


def test_pipelined_throughput_smoke(tmp_path):
    """A few hundred pipelined ticks on CPU: (a) the jitted fused act
    compiled exactly ONCE — a traced-vs-static slip on the tick counter
    would recompile every tick and this counter would explode; (b) every
    tick ran host feed work while a dispatch was in flight (dispatch
    precedes advance in the schedule), i.e. the overlap the pipeline
    exists for is nonzero."""
    ticks = 300
    res = bounded_actor_run(_opt(1, tmp_path, "pipelined"), ticks)
    h = res["harness"]
    assert h.engine.jit_cache_size() == 1, \
        "fused act retraced mid-run (per-tick recompilation)"
    t = res["timer_ms"]
    # one dispatch per tick (+ the pipeline-priming one), one sync each
    assert t["actor/time_dispatch_calls"] == ticks + 1
    assert t["actor/time_sync_calls"] == ticks
    # the overlapped host work is real, not a zero-length no-op
    assert t["actor/time_advance_calls"] == ticks
    overlapped_ms = t["actor/time_advance_ms"] * ticks
    assert overlapped_ms > 0.0


def test_recurrent_pipelined_no_retrace(tmp_path):
    """The recurrent fused act takes the reset mask + tick as traced
    args — neither may trigger per-tick recompiles."""
    res = bounded_actor_run(
        _opt(13, tmp_path, "pipelined", seq_len=8, seq_overlap=4), 80)
    assert res["harness"].engine.jit_cache_size() == 1


# ---------------------------------------------------------------------------
# batched backend: the shared inference server serves identical streams
# ---------------------------------------------------------------------------


def _server_for(opt, spec):
    from pytorch_distributed_tpu.factory import build_model, init_params
    from pytorch_distributed_tpu.agents.inference import InferenceServer
    from pytorch_distributed_tpu.agents.param_store import (
        ParamStore, make_flattener,
    )

    model = build_model(opt, spec)
    flat0, _ = make_flattener(init_params(opt, spec, model, seed=0))
    store = ParamStore(flat0.size)
    store.publish(flat0)
    return InferenceServer(opt, spec, store)


def test_batched_backend_matches_inline(tmp_path):
    """On a same-device (CPU) server the SEED-style batched backend is
    bit-identical to the local loops: per-row fold_in keys make action
    randomness independent of batching, and the server runs the same
    jitted program over the same published weights."""
    from pytorch_distributed_tpu.factory import probe_env

    opt_b = _opt(1, tmp_path, "batched")
    spec = probe_env(opt_b)
    server = _server_for(opt_b, spec)
    client = server.make_client(0)
    server.start()
    try:
        batched = bounded_actor_run(opt_b, 50, spec=spec,
                                    inference=client)
    finally:
        server.stop()
    inline = bounded_actor_run(_opt(1, tmp_path, "inline"), 50, spec=spec)
    _assert_streams_equal(inline["stream"], batched["stream"])
    assert server.stats["batches"] > 0
    assert server.stats["rows"] >= 50 * 3


def test_batched_backend_multi_client_rows(tmp_path):
    """Two clients coalesced into one sweep still get their own rows
    back: submit both before the server drains, forcing the
    concat/pad/scatter path at least once."""
    from pytorch_distributed_tpu.factory import probe_env
    from pytorch_distributed_tpu.models.policies import apex_epsilons
    from pytorch_distributed_tpu.utils.rngs import process_key

    opt = _opt(1, tmp_path, "batched")
    spec = probe_env(opt)
    server = _server_for(opt, spec)
    c0, c1 = server.make_client(0), server.make_client(1)
    for ind, c in ((0, c0), (1, c1)):
        c.begin_session(
            base_key=np.asarray(process_key(opt.seed, "actor", ind)),
            eps=apex_epsilons(ind, 2, 3))
    obs = np.zeros((3, 8), np.float32)
    obs[:, 0] = 1.0
    # enqueue both requests BEFORE the server thread starts draining
    h0 = c0.submit(obs, 0)
    h1 = c1.submit(obs, 0)
    server.start()
    try:
        p0 = c0.collect(h0, timeout=120.0)
        p1 = c1.collect(h1, timeout=120.0)
    finally:
        server.stop()
    assert p0.shape == (3, 3) and p1.shape == (3, 3)
    # rows from the same obs under the same weights: q_max must agree
    # across clients; actions may differ (per-client keys/eps)
    np.testing.assert_allclose(p0[2], p1[2], rtol=1e-6)


def test_batched_client_frame_packing():
    """The client elects the frame-packed wire mode exactly when the
    roll property holds: first submit full (seeds the server stack),
    rolled ticks packed (only the newest HxW frame ships), any broken
    roll — an env reset — full again."""
    from pytorch_distributed_tpu.agents.inference import InferenceClient

    sent = []

    import queue

    class _Q:
        def put(self, item):
            sent.append(item)

    c = InferenceClient(0, "dqn", _Q(), queue.Queue())
    c.begin_session(base_key=np.zeros(2, np.uint32),
                    eps=np.zeros(2, np.float32))
    obs0 = np.arange(2 * 4 * 3 * 3, dtype=np.uint8).reshape(2, 4, 3, 3)
    c.submit(obs0, 0)
    rolled = np.concatenate(
        [obs0[:, 1:], np.full((2, 1, 3, 3), 7, np.uint8)], axis=1)
    c.submit(rolled, 1)
    reset = np.zeros_like(obs0)  # env reset: fresh stack, roll broken
    c.submit(reset, 2)
    rolled2 = np.concatenate(
        [reset[:, 1:], np.full((2, 1, 3, 3), 9, np.uint8)], axis=1)
    c.submit(rolled2, 3)
    modes = [req[3] for req in sent]
    assert modes == ["full", "packed", "full", "packed"]
    assert sent[1][4].shape == (2, 3, 3)  # newest frame only
    np.testing.assert_array_equal(sent[1][4], np.full((2, 3, 3), 7))
    assert sent[2][4].shape == obs0.shape  # reset re-ships the stack


def test_batched_backend_frame_packed_pixels(tmp_path):
    """End-to-end packed path on the real rolling-stack env (pong-sim
    pixels): the server reconstructs stacks on device from newest-frame
    uploads, and the stream still matches the inline oracle bit for bit
    — including across episode resets, which force full re-uploads."""
    from pytorch_distributed_tpu.factory import probe_env

    kw = dict(num_envs_per_actor=2, early_stop=12)  # quick resets
    opt_b = _opt(4, tmp_path, "batched", **kw)
    spec = probe_env(opt_b)
    server = _server_for(opt_b, spec)
    client = server.make_client(0)
    server.start()
    try:
        batched = bounded_actor_run(opt_b, 30, spec=spec,
                                    inference=client)
    finally:
        server.stop()
    inline = bounded_actor_run(_opt(4, tmp_path, "inline", **kw), 30,
                               spec=spec)
    _assert_streams_equal(inline["stream"], batched["stream"])


def test_resolve_actor_backend_downgrades(tmp_path):
    from pytorch_distributed_tpu.factory import resolve_actor_backend

    opt = _opt(1, tmp_path, "batched")
    with pytest.warns(UserWarning, match="no InferenceClient"):
        assert resolve_actor_backend(opt, None) == "pipelined"
    opt_r = _opt(13, tmp_path, "batched", seq_len=8, seq_overlap=4)
    with pytest.warns(UserWarning, match="recurrent"):
        assert resolve_actor_backend(opt_r, object()) == "pipelined"
    opt_bad = _opt(1, tmp_path, "pipelined")
    opt_bad.env_params.actor_backend = "warp"
    with pytest.raises(ValueError, match="warp"):
        resolve_actor_backend(opt_bad)
    assert resolve_actor_backend(_opt(1, tmp_path, "inline")) == "inline"


# ---------------------------------------------------------------------------
# param prefetcher: swaps never block, remote stores still poll
# ---------------------------------------------------------------------------


def test_param_prefetcher_basic():
    import time

    from pytorch_distributed_tpu.agents.param_store import (
        ParamPrefetcher, ParamStore,
    )

    store = ParamStore(4)
    v1 = store.publish(np.arange(4, dtype=np.float32))
    pf = ParamPrefetcher(store, lambda f: f * 2.0, start_version=v1,
                         poll_secs=0.01)
    try:
        assert pf.take() is None  # nothing newer than v1
        v2 = store.publish(np.ones(4, dtype=np.float32))
        deadline = time.monotonic() + 5.0
        got = None
        while got is None and time.monotonic() < deadline:
            got = pf.take()
            time.sleep(0.01)
        assert got is not None
        tree, version = got
        assert version == v2
        np.testing.assert_array_equal(tree, np.full(4, 2.0, np.float32))
        assert pf.take() is None  # consumed
    finally:
        pf.close()


def test_param_prefetcher_versionless_store():
    """A DCN RemoteParamStore exposes no cheap ``version`` property —
    the fetch itself is the probe.  The prefetcher must still deliver."""
    import time

    from pytorch_distributed_tpu.agents.param_store import (
        ParamPrefetcher, ParamStore,
    )

    inner = ParamStore(2)

    class _RemoteLike:
        def fetch(self, min_version=0):
            return inner.fetch(min_version)

    pf = ParamPrefetcher(_RemoteLike(), lambda f: f, start_version=0,
                         remote_poll_secs=0.01)
    try:
        inner.publish(np.array([3.0, 4.0], np.float32))
        deadline = time.monotonic() + 5.0
        got = None
        while got is None and time.monotonic() < deadline:
            got = pf.take()
            time.sleep(0.01)
        assert got is not None and got[1] == 1
    finally:
        pf.close()


# ---------------------------------------------------------------------------
# apex_epsilons: the fleet exploration ladder (previously untested)
# ---------------------------------------------------------------------------


def test_apex_epsilons_formula():
    """env j of actor i takes slot i*N+j of the num_actors*N ladder,
    each slot getting eps ** (1 + slot/(total-1) * alpha) — the Ape-X
    schedule (Horgan et al. 2018; reference dqn_actor.py:33-36)."""
    from pytorch_distributed_tpu.models.policies import (
        apex_epsilon, apex_epsilons,
    )

    eps, alpha = 0.4, 7.0
    A, N = 4, 3
    total = A * N
    for i in range(A):
        got = apex_epsilons(i, A, N, eps, alpha)
        assert got.shape == (N,) and got.dtype == np.float32
        for j in range(N):
            slot = i * N + j
            expect = eps ** (1.0 + slot / (total - 1) * alpha)
            np.testing.assert_allclose(got[j], expect, rtol=1e-6)
            np.testing.assert_allclose(
                got[j], apex_epsilon(slot, total, eps, alpha), rtol=1e-6)
    # monotone: later fleet slots explore less
    ladder = np.concatenate([apex_epsilons(i, A, N, eps, alpha)
                             for i in range(A)])
    assert np.all(np.diff(ladder) < 0)


def test_apex_epsilons_stable_across_reshape():
    """The FLEET ladder depends only on num_actors * num_envs: reshaping
    4x3 into 6x2 or 12x1 yields the same 12 epsilons in the same global
    slot order — so retopologizing a fleet never changes its exploration
    mix."""
    from pytorch_distributed_tpu.models.policies import apex_epsilons

    def ladder(A, N):
        return np.concatenate([apex_epsilons(i, A, N) for i in range(A)])

    ref = ladder(4, 3)
    np.testing.assert_allclose(ladder(6, 2), ref, rtol=1e-7)
    np.testing.assert_allclose(ladder(12, 1), ref, rtol=1e-7)
    np.testing.assert_allclose(ladder(1, 12), ref, rtol=1e-7)


def test_apex_epsilons_single_actor_debug_value():
    """num_actors*num_envs == 1 keeps the reference's 0.1 debug branch."""
    from pytorch_distributed_tpu.models.policies import apex_epsilons

    np.testing.assert_allclose(apex_epsilons(0, 1, 1), [0.1])
