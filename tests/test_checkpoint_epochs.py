"""Crash-consistent checkpoint epochs (utils/checkpoint.py): atomic
manifest commit, torn/digest-mismatch rejection, retention GC, geometry
validation (CheckpointMismatch), cross-family snapshot interchange, and
the kill-resume drills — SIGKILL at exact write points via the
``CKPT_FAULTS`` schedule (utils/faults.py ``kill@FRAME``), then assert a
subsequent resume always finds a complete, digest-valid epoch with
mutually consistent counters.  The slow tier runs the same drill on the
full training topology, plus the SIGTERM-preemption path
(runtime.py)."""

import os
import re
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.memory.feeder import QueueOwner
from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.memory.sequence_replay import (
    Segment, SequenceReplay,
)
from pytorch_distributed_tpu.memory.shared_replay import SharedReplay
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils.experience import Transition

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
KILL_CHILD = os.path.join(_HERE, "_ckpt_kill_child.py")
TOPO_CHILD = os.path.join(_HERE, "_kill_resume_child.py")


def geom(capacity, shape=(4,), dtype=np.uint8):
    return dict(capacity=capacity, state_shape=shape, action_shape=(),
                state_dtype=dtype, action_dtype=np.int32)


def fill(mem, n, seed=0, priorities=False):
    rng = np.random.default_rng(seed)
    for i in range(n):
        mem.feed(Transition(
            state0=rng.integers(0, 255, (4,)).astype(np.uint8),
            action=np.int32(i % 3), reward=np.float32(i),
            gamma_n=np.float32(0.99),
            state1=rng.integers(0, 255, (4,)).astype(np.uint8),
            terminal1=np.float32(i % 7 == 0)),
            float(i % 5) if priorities else None)


def tiny_state(step=0):
    import jax.numpy as jnp

    return {"w": jnp.full((16,), float(step)), "step": jnp.int32(step)}


def _child_env(extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("CKPT_FAULTS", None)
    # children need no virtual multi-device mesh; a 1-device CPU backend
    # starts faster
    env["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", "")).strip()
    if extra:
        env.update(extra)
    return env


def run_child(script, args, extra_env=None, timeout=240):
    p = subprocess.run(
        [sys.executable, script, *map(str, args)], env=_child_env(extra_env),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=timeout)
    return p.returncode, p.stdout.decode()


# ---------------------------------------------------------------------------
# epoch subsystem units
# ---------------------------------------------------------------------------

class TestEpochSubsystem:
    def test_save_resolve_roundtrip(self, tmp_path):
        import jax.numpy as jnp

        mn = str(tmp_path / "m")
        mem = SharedReplay(**geom(32))
        fill(mem, 20)
        ed = ckpt.save_epoch(mn, state=tiny_state(7), memory=mem,
                             extras={"learner_step": 7, "actor_step": 21,
                                     "best_eval_reward": 1.5})
        assert os.path.exists(os.path.join(ed, ckpt.MANIFEST))
        info = ckpt.resolve_epoch(mn)
        assert (info.epoch, info.learner_step) == (0, 7)
        assert info.has_state and info.has_replay
        assert info.extras["actor_step"] == 21
        assert info.extras["best_eval_reward"] == 1.5
        st = ckpt.load_epoch_state(info, tiny_state(0))
        assert int(st["step"]) == 7
        assert float(np.asarray(st["w"])[0]) == 7.0
        mem2 = SharedReplay(**geom(32))
        assert ckpt.load_epoch_replay(info, mem2) == 20
        assert mem2.size == 20
        np.testing.assert_array_equal(
            np.sort(mem2._np_reward[:20]), np.arange(20, dtype=np.float32))
        # jnp only used via tiny_state; silence linters
        assert jnp is not None

    def test_torn_epoch_skipped_and_cleared(self, tmp_path):
        mn = str(tmp_path / "m")
        for s in (5, 10):
            ckpt.save_epoch(mn, state=tiny_state(s),
                            extras={"learner_step": s})
        root = ckpt.ckpt_root(mn)
        # tear the newest: a crash between the artifact writes and the
        # manifest commit leaves exactly this
        os.remove(os.path.join(root, "epoch_1", ckpt.MANIFEST))
        info = ckpt.resolve_epoch(mn)
        assert (info.epoch, info.learner_step) == (0, 5)
        rep = ckpt.fsck(root)
        assert rep["violations"] == []  # torn-uncommitted is debris, not a lie
        assert rep["newest_complete"] == 0
        # the next save reuses the torn slot and the numbering continues
        ckpt.save_epoch(mn, state=tiny_state(15),
                        extras={"learner_step": 15})
        info2 = ckpt.resolve_epoch(mn)
        assert (info2.epoch, info2.learner_step) == (1, 15)

    def test_digest_mismatch_rejected(self, tmp_path):
        mn = str(tmp_path / "m")
        mem = SharedReplay(**geom(32))
        fill(mem, 10)
        for s in (5, 10):
            ckpt.save_epoch(mn, state=tiny_state(s), memory=mem,
                            extras={"learner_step": s})
        root = ckpt.ckpt_root(mn)
        with open(os.path.join(root, "epoch_1", "replay.npz"), "r+b") as f:
            f.seek(40)
            f.write(b"\xff\xff\xff")
        info = ckpt.resolve_epoch(mn)
        assert (info.epoch, info.learner_step) == (0, 5)
        rep = ckpt.fsck(root)
        assert any("digest mismatch" in v for v in rep["violations"])
        assert rep["newest_complete"] == 0

    def test_manifest_garbage_rejected(self, tmp_path):
        mn = str(tmp_path / "m")
        for s in (5, 10):
            ckpt.save_epoch(mn, state=tiny_state(s),
                            extras={"learner_step": s})
        root = ckpt.ckpt_root(mn)
        with open(os.path.join(root, "epoch_1", ckpt.MANIFEST), "w") as f:
            f.write("{not json")
        assert ckpt.resolve_epoch(mn).epoch == 0
        assert any("unreadable" in v for v in ckpt.fsck(root)["violations"])

    def test_extras_step_inconsistency_is_a_violation(self, tmp_path):
        import json

        mn = str(tmp_path / "m")
        ckpt.save_epoch(mn, state=tiny_state(5), extras={"learner_step": 5})
        ed = os.path.join(ckpt.ckpt_root(mn), "epoch_0")
        with open(os.path.join(ed, ckpt.MANIFEST)) as f:
            man = json.load(f)
        man["learner_step"] = 999  # counters no longer one triple
        # re-digest extras stays valid; only the cross-check must trip
        with open(os.path.join(ed, ckpt.MANIFEST), "w") as f:
            json.dump(man, f)
        status, bad = ckpt.verify_epoch(ed)
        assert status == "corrupt"
        assert any("learner_step" in v for v in bad)

    def test_retention_gc(self, tmp_path):
        mn = str(tmp_path / "m")
        for s in range(5):
            ckpt.save_epoch(mn, state=tiny_state(s),
                            extras={"learner_step": s}, retain=2)
        root = ckpt.ckpt_root(mn)
        kept = sorted(os.listdir(root))
        assert kept == ["epoch_3", "epoch_4"]
        assert ckpt.resolve_epoch(mn).learner_step == 4

    def test_resolve_empty_and_missing(self, tmp_path):
        assert ckpt.resolve_epoch(str(tmp_path / "none")) is None
        os.makedirs(str(tmp_path / "e_ckpt"))
        assert ckpt.resolve_epoch(str(tmp_path / "e")) is None
        rep = ckpt.fsck(str(tmp_path / "missing_ckpt"))
        assert rep["violations"]  # no such directory


class TestLegacySingleSnapshot:
    def test_save_is_publish_by_rename_not_overwrite(self, tmp_path):
        import jax.numpy as jnp

        mn = str(tmp_path / "m")
        ckpt.save_train_state(mn, {"w": jnp.full((4,), 1.0)})
        ckpt.save_train_state(mn, {"w": jnp.full((4,), 2.0)})
        r = ckpt.restore_train_state(mn, {"w": jnp.zeros((4,))})
        assert float(np.asarray(r["w"])[0]) == 2.0
        # no stray publish-window dirs after a clean save
        assert not os.path.isdir(ckpt.state_dir(mn) + ".new")
        assert not os.path.isdir(ckpt.state_dir(mn) + ".old")

    def test_crash_window_prefers_newer_complete_new(self, tmp_path):
        """With ``_state`` absent (crash between the two publish renames)
        ``.new`` is complete and one interval NEWER than the parked
        ``.old`` — restore must take it, and the next save must heal it
        into place instead of purging the store's only copies."""
        import jax.numpy as jnp

        mn = str(tmp_path / "m")
        path = ckpt.state_dir(mn)
        # fabricate the exact crash-window layout: v1 parked at .old,
        # v2 complete at .new, nothing published (saves heal the window,
        # so build the .old from a scratch model name)
        other = str(tmp_path / "other")
        ckpt.save_train_state(other, {"w": jnp.full((4,), 1.0)})
        os.rename(ckpt.state_dir(other), path + ".old")
        ckpt.save_train_state(mn, {"w": jnp.full((4,), 2.0)})
        os.rename(path, path + ".new")
        r = ckpt.restore_train_state(mn, {"w": jnp.zeros((4,))})
        assert float(np.asarray(r["w"])[0]) == 2.0  # the newer one
        # the next save heals rather than deletes: even a SIGKILL right
        # after its debris pass must leave a restorable state
        ckpt.save_train_state(mn, {"w": jnp.full((4,), 5.0)})
        r2 = ckpt.restore_train_state(mn, {"w": jnp.zeros((4,))})
        assert float(np.asarray(r2["w"])[0]) == 5.0

    def test_best_score_sidecar_roundtrip(self, tmp_path):
        mn = str(tmp_path / "m")
        assert ckpt.load_best_score(mn) == float("-inf")
        ckpt.save_best_score(mn, 17.5, step=123)
        assert ckpt.load_best_score(mn) == 17.5
        # unreadable sidecar degrades to -inf, never crashes a resume
        with open(ckpt.best_score_path(mn), "w") as f:
            f.write("{torn")
        assert ckpt.load_best_score(mn) == float("-inf")

    def test_restore_falls_back_across_crash_window(self, tmp_path):
        import jax.numpy as jnp

        mn = str(tmp_path / "m")
        ckpt.save_train_state(mn, {"w": jnp.full((4,), 3.0)})
        path = ckpt.state_dir(mn)
        # crash between the two publish renames: good state parked at .old
        os.rename(path, path + ".old")
        r = ckpt.restore_train_state(mn, {"w": jnp.zeros((4,))})
        assert float(np.asarray(r["w"])[0]) == 3.0
        # torn .new debris next to it must not poison the fallback
        os.makedirs(path + ".new")
        with open(os.path.join(path + ".new", "junk"), "w") as f:
            f.write("torn")
        r2 = ckpt.restore_train_state(mn, {"w": jnp.zeros((4,))})
        assert float(np.asarray(r2["w"])[0]) == 3.0
        # and the next save clears the debris and publishes cleanly
        ckpt.save_train_state(mn, {"w": jnp.full((4,), 4.0)})
        r3 = ckpt.restore_train_state(mn, {"w": jnp.zeros((4,))})
        assert float(np.asarray(r3["w"])[0]) == 4.0


# ---------------------------------------------------------------------------
# geometry validation (CheckpointMismatch)
# ---------------------------------------------------------------------------

class TestMismatch:
    def snap_of(self, **kw):
        mem = SharedReplay(**geom(16, **kw))
        fill(mem, 8)
        return mem.snapshot()

    def test_shape_change_fails_loudly(self):
        snap = self.snap_of()
        live = SharedReplay(**geom(16, shape=(5,)))
        with pytest.raises(ckpt.CheckpointMismatch, match="state rows"):
            ckpt.validate_snapshot(live, snap)

    def test_dtype_change_fails_loudly(self):
        snap = self.snap_of()
        live = SharedReplay(**geom(16, dtype=np.float32))
        with pytest.raises(ckpt.CheckpointMismatch, match="dtype"):
            ckpt.validate_snapshot(live, snap)

    def test_family_change_fails_loudly(self):
        snap = self.snap_of()
        live = SequenceReplay(capacity=8, seq_len=4, state_shape=(4,),
                              lstm_dim=3, state_dtype=np.float32)
        with pytest.raises(ckpt.CheckpointMismatch, match="segment"):
            ckpt.validate_snapshot(live, snap)

    def test_seq_len_change_fails_loudly(self):
        a = SequenceReplay(capacity=8, seq_len=4, state_shape=(4,),
                           lstm_dim=3, state_dtype=np.float32)
        a.feed(Segment(obs=np.zeros((5, 4), np.float32),
                       action=np.zeros(4, np.int32),
                       reward=np.zeros(4, np.float32),
                       terminal=np.zeros(4, np.float32),
                       mask=np.ones(4, np.float32),
                       c0=np.zeros(3, np.float32),
                       h0=np.zeros(3, np.float32)))
        live = SequenceReplay(capacity=8, seq_len=6, state_shape=(4,),
                              lstm_dim=3, state_dtype=np.float32)
        with pytest.raises(ckpt.CheckpointMismatch, match="obs rows"):
            ckpt.validate_snapshot(live, a.snapshot())

    def test_capacity_change_is_legal(self, tmp_path):
        mn = str(tmp_path / "m")
        mem = SharedReplay(**geom(32))
        fill(mem, 32)
        ckpt.save_epoch(mn, memory=mem, extras={"learner_step": 1})
        small = SharedReplay(**geom(8))
        # the reported count is what actually FIT, not the saved total
        assert ckpt.load_epoch_replay(ckpt.resolve_epoch(mn), small) == 8
        assert small.size == 8  # newest rows that fit

    def test_legacy_load_replay_validates_too(self, tmp_path):
        mn = str(tmp_path / "m")
        mem = SharedReplay(**geom(16))
        fill(mem, 8)
        ckpt.save_replay(mn, mem)
        live = SharedReplay(**geom(16, shape=(5,)))
        with pytest.raises(ckpt.CheckpointMismatch):
            ckpt.load_replay(mn, live)


# ---------------------------------------------------------------------------
# cross-family snapshot interchange (satellite: round-trips across
# memory families)
# ---------------------------------------------------------------------------

class TestCrossFamily:
    def test_host_per_to_device_per_leaf_agreement(self):
        import jax

        from pytorch_distributed_tpu.memory.device_per import DevicePerReplay

        host = PrioritizedReplay(**geom(64))
        fill(host, 30, priorities=True)
        host.update_priorities(np.arange(10),
                               np.linspace(0.2, 2.5, 10))
        snap = host.snapshot()
        dev = DevicePerReplay(**geom(64))
        dev.restore(snap)
        leaves_host = host.sum_tree.get(np.arange(host.size))
        leaves_dev = np.asarray(
            jax.device_get(dev.state.priority))[:host.size]
        np.testing.assert_allclose(leaves_dev, leaves_host, rtol=1e-5)
        # running max agrees in the shared base unit (device stores
        # p^alpha — memory/device_per.py snapshot/restore conversion)
        mx_dev = float(jax.device_get(dev.state.max_priority))
        np.testing.assert_allclose(mx_dev ** (1.0 / dev.alpha),
                                   host.max_priority, rtol=1e-5)

    def test_device_per_to_host_per_leaf_agreement(self):
        import jax

        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay, per_update_priorities,
        )

        dev = DevicePerReplay(**geom(64))
        rng = np.random.default_rng(0)
        n = 24
        dev.feed_chunk(Transition(
            state0=rng.integers(0, 255, (n, 4)).astype(np.uint8),
            action=np.zeros(n, np.int32),
            reward=np.arange(n, dtype=np.float32),
            gamma_n=np.full(n, 0.99, np.float32),
            state1=rng.integers(0, 255, (n, 4)).astype(np.uint8),
            terminal1=np.zeros(n, np.float32)))
        dev.state = per_update_priorities(
            dev.state, np.arange(n, dtype=np.int32),
            np.linspace(0.1, 3.0, n).astype(np.float32), alpha=dev.alpha)
        leaves_dev = np.asarray(jax.device_get(dev.state.priority))[:n]
        host = PrioritizedReplay(**geom(64))
        host.restore(dev.snapshot())
        assert host.size == n
        np.testing.assert_allclose(host.sum_tree.get(np.arange(n)),
                                   leaves_dev, rtol=1e-5)
        # both agree on what they'd sample
        batch = host.sample(8, np.random.default_rng(1))
        assert np.isfinite(batch.weight).all()

    def test_device_ring_nchw_nhwc_snapshot_parity(self):
        from pytorch_distributed_tpu.memory.device_replay import DeviceReplay

        g = dict(capacity=16, state_shape=(2, 4, 4), action_shape=(),
                 state_dtype=np.uint8, action_dtype=np.int32)
        rng = np.random.default_rng(0)
        n = 10
        chunk = Transition(
            state0=rng.integers(0, 255, (n, 2, 4, 4)).astype(np.uint8),
            action=np.zeros(n, np.int32),
            reward=np.arange(n, dtype=np.float32),
            gamma_n=np.full(n, 0.99, np.float32),
            state1=rng.integers(0, 255, (n, 2, 4, 4)).astype(np.uint8),
            terminal1=np.zeros(n, np.float32))
        a = DeviceReplay(**g, channels_last=False)
        b = DeviceReplay(**g, channels_last=True)
        a.feed_chunk(chunk)
        b.feed_chunk(chunk)
        sa, sb = a.snapshot(), b.snapshot()
        assert set(sa) == set(sb)
        for k in sa:  # checkpoints are layout-independent (public NCHW)
            np.testing.assert_array_equal(sa[k], sb[k])
        # an NCHW snapshot restores into an NHWC ring and round-trips
        c = DeviceReplay(**g, channels_last=True)
        assert c.restore(sa) == n
        sc = c.snapshot()
        for k in sa:
            np.testing.assert_array_equal(sc[k], sa[k])

    def test_host_device_sequence_interchange(self):
        import jax

        from pytorch_distributed_tpu.memory.device_sequence import (
            DeviceSequenceReplay,
        )

        def seg(i):
            return Segment(
                obs=np.full((9, 4), float(i), np.float32),
                action=np.full(8, i, np.int32),
                reward=np.full(8, float(i), np.float32),
                terminal=np.zeros(8, np.float32),
                mask=np.ones(8, np.float32),
                c0=np.full(3, float(i), np.float32),
                h0=np.full(3, -float(i), np.float32))

        host = SequenceReplay(capacity=16, seq_len=8, state_shape=(4,),
                              lstm_dim=3, state_dtype=np.float32)
        for i in range(10):
            host.feed(seg(i))
        host.update_priorities(np.arange(10), np.linspace(0.1, 2.0, 10))
        dev = DeviceSequenceReplay(capacity=16, seq_len=8,
                                   state_shape=(4,), lstm_dim=3,
                                   state_dtype=np.float32)
        assert dev.restore(host.snapshot()) == 10
        st = jax.device_get(dev.state)
        np.testing.assert_allclose(np.asarray(st.reward)[:10, 0],
                                   np.arange(10, dtype=np.float32))
        np.testing.assert_allclose(np.asarray(st.priority)[:10],
                                   host.priority[:10], rtol=1e-5)
        # and back: the device snapshot refills a fresh host ring
        host2 = SequenceReplay(capacity=16, seq_len=8, state_shape=(4,),
                               lstm_dim=3, state_dtype=np.float32)
        assert host2.restore(dev.snapshot()) == 10
        np.testing.assert_allclose(host2.reward[:10, 0],
                                   np.arange(10, dtype=np.float32))
        np.testing.assert_allclose(host2.priority[:10], host.priority[:10],
                                   rtol=1e-5)

    def test_epoch_save_drains_queued_chunks(self, tmp_path):
        """Single-owner coordination: rows still sitting in the feeder
        queue at save time must land in the SAME epoch as the state."""
        mn = str(tmp_path / "m")
        owner = QueueOwner(SharedReplay(**geom(64)))
        feeder = owner.make_feeder(chunk=4)
        fill(feeder, 12)  # 3 flushed chunks, all still queued
        try:
            # mp.Queue delivers through a background feeder thread; wait
            # for the pipe (in the learner the drain cadence absorbs this)
            deadline = time.monotonic() + 10
            while owner.size < 12 and time.monotonic() < deadline:
                owner.drain()
                time.sleep(0.02)
            ckpt.save_epoch(mn, memory=owner, extras={"learner_step": 3})
            info = ckpt.resolve_epoch(mn)
            assert info.manifest["artifacts"]["replay.npz"]["rows"] == 12
            fresh = SharedReplay(**geom(64))
            assert ckpt.load_epoch_replay(info, fresh) == 12
        finally:
            owner.close()

    def test_field_check_contract(self):
        """The CI contract the tooling satellite adds — run it here so the
        fast tier catches a one-sided snapshot/restore surface at PR
        time, not at field time."""
        sys.path.insert(0, _REPO)
        from tools.field_check import check_snapshot_restore_contract

        out = check_snapshot_restore_contract()
        assert "SequenceReplay" in out["round_tripped"]
        assert out["scanned"] >= 8


# ---------------------------------------------------------------------------
# kill-resume drills (fast tier: checkpoint subsystem in a child process)
# ---------------------------------------------------------------------------

class TestKillDrill:
    # write points within the SECOND save (frames 6..11): the first epoch
    # is committed, then the process dies mid-Orbax-write (7), between
    # the state and replay writes (8), mid-replay-publish (9), with all
    # artifacts durable but uncommitted (10), and right after the
    # manifest commit (11)
    @pytest.mark.parametrize("frame", [7, 8, 9, 10, 11])
    @pytest.mark.timeout(240)
    def test_sigkill_mid_save_never_loses_the_store(self, tmp_path, frame):
        mn = str(tmp_path / "m")
        rc, out = run_child(KILL_CHILD, [mn, 4],
                            {"CKPT_FAULTS": f"kill@{frame}"})
        assert rc == -signal.SIGKILL, out
        committed = [int(line.split()[2]) for line in out.splitlines()
                     if line.startswith("COMMITTED")]
        assert committed, out  # the first save always survives
        # the surviving store: zero violations, a resolvable epoch whose
        # counters are one consistent triple
        rep = ckpt.fsck(ckpt.ckpt_root(mn))
        assert rep["violations"] == [], rep
        info = ckpt.resolve_epoch(mn)
        assert info is not None
        assert info.learner_step >= committed[-1]  # no regression
        assert info.extras["actor_step"] == info.learner_step * 3
        st = ckpt.load_epoch_state(info, tiny_state(0))
        assert int(st["step"]) == info.learner_step
        mem = SharedReplay(**geom(64))
        rows = ckpt.load_epoch_replay(info, mem)
        assert rows == mem.size > 0
        # a resumed writer clears the torn debris and continues numbering
        nxt = info.learner_step + 10
        ckpt.save_epoch(mn, state=tiny_state(nxt), memory=mem,
                        extras={"learner_step": nxt,
                                "actor_step": nxt * 3})
        assert ckpt.fsck(ckpt.ckpt_root(mn))["violations"] == []
        info2 = ckpt.resolve_epoch(mn)
        assert (info2.epoch, info2.learner_step) == (info.epoch + 1, nxt)


# ---------------------------------------------------------------------------
# full-topology drills (slow tier)
# ---------------------------------------------------------------------------

def _poll_epoch(model_name, timeout=300.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            info = ckpt.resolve_epoch(model_name)
        except Exception:  # noqa: BLE001 - GC race mid-poll
            info = None
        if info is not None:
            return info
        time.sleep(0.5)
    raise AssertionError(f"no complete epoch appeared under "
                         f"{ckpt.ckpt_root(model_name)}")


def _final_line(out):
    m = re.search(r"FINAL lstep=(\d+) actor=(\d+) preempted=(\d)", out)
    assert m, out
    return int(m.group(1)), int(m.group(2)), int(m.group(3))


class TestTopologyDrills:
    @pytest.mark.slow
    @pytest.mark.timeout(900)
    def test_sigkill_mid_save_then_resume_continues(self, tmp_path):
        """The acceptance drill: a real training run SIGKILLed between
        the state and replay writes of its second epoch save; the
        ``--resume`` run must find a complete digest-valid epoch and
        continue with learner step, replay size and clock counters
        mutually consistent."""
        mn = os.path.join(str(tmp_path), "models", "kr")
        # frame 8 = second save's after_state point (utils/checkpoint.py
        # _FRAME_POINTS): state durable, replay not yet written
        rc, out = run_child(TOPO_CHILD, [str(tmp_path), "kr", 60, "auto"],
                            {"CKPT_FAULTS": "kill@8"}, timeout=600)
        assert rc == -signal.SIGKILL, out
        rep = ckpt.fsck(ckpt.ckpt_root(mn))
        assert rep["violations"] == [], rep
        info = ckpt.resolve_epoch(mn)
        assert info is not None and info.learner_step > 0
        assert info.extras["replay_size"] > 0
        a1 = info.extras["actor_step"]

        rc2, out2 = run_child(TOPO_CHILD,
                              [str(tmp_path), "kr", 80, "must"],
                              timeout=600)
        assert rc2 == 0, out2
        assert "resumed epoch" in out2
        lstep, _actor, _pre = _final_line(out2)
        assert lstep >= 80
        final = ckpt.resolve_epoch(mn)
        assert final.learner_step >= 80 >= info.learner_step
        assert final.extras["actor_step"] >= a1  # counters never regress
        assert final.extras["replay_size"] > 0
        assert ckpt.fsck(ckpt.ckpt_root(mn))["violations"] == []

    @pytest.mark.slow
    @pytest.mark.timeout(900)
    def test_sigterm_preemption_writes_final_epoch_then_resumes(
            self, tmp_path):
        """SIGTERM = preemption notice (runtime.py): trip stop, drain,
        write a final epoch, exit 0 — and the next --resume run carries
        on from it."""
        mn = os.path.join(str(tmp_path), "models", "pt")
        proc = subprocess.Popen(
            [sys.executable, TOPO_CHILD, str(tmp_path), "pt", "1000000",
             "auto"],
            env=_child_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
        try:
            seen = _poll_epoch(mn, timeout=300.0)
            proc.send_signal(signal.SIGTERM)
            out = proc.communicate(timeout=300)[0].decode()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=60)
        assert proc.returncode == 0, out
        assert "preemption notice" in out
        lstep, _actor, preempted = _final_line(out)
        assert preempted == 1
        final = ckpt.resolve_epoch(mn)
        # the final epoch is the preempted run's LAST state, not a stale
        # cadence save
        assert final.learner_step >= seen.learner_step
        assert final.learner_step >= lstep - 10  # within one cadence
        assert ckpt.fsck(ckpt.ckpt_root(mn))["violations"] == []

        rc2, out2 = run_child(
            TOPO_CHILD,
            [str(tmp_path), "pt", final.learner_step + 20, "must"],
            timeout=600)
        assert rc2 == 0, out2
        lstep2, _a2, _p2 = _final_line(out2)
        assert lstep2 >= final.learner_step + 20
        assert ckpt.resolve_epoch(mn).learner_step >= final.learner_step
