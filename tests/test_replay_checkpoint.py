"""Replay-contents checkpointing (utils/checkpoint.py save_replay /
load_replay) — the resume leg the reference never had (SURVEY.md §5
"Not checkpointed: ... replay contents")."""

from __future__ import annotations

import numpy as np
import pytest

from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.memory.shared_replay import SharedReplay
from pytorch_distributed_tpu.utils import checkpoint as ckpt
from pytorch_distributed_tpu.utils.experience import Transition


def fill(mem, n, seed=0, priorities=False):
    rng = np.random.default_rng(seed)
    for i in range(n):
        t = Transition(
            state0=rng.integers(0, 255, size=(4,)).astype(np.uint8),
            action=np.int32(i % 3),
            reward=np.float32(i),
            gamma_n=np.float32(0.99),
            state1=rng.integers(0, 255, size=(4,)).astype(np.uint8),
            terminal1=np.float32(i % 7 == 0),
        )
        mem.feed(t, float(i % 5) if priorities else None)


def geom(capacity):
    return dict(capacity=capacity, state_shape=(4,), action_shape=(),
                state_dtype=np.uint8, action_dtype=np.int32)


def test_shared_roundtrip(tmp_path):
    a = SharedReplay(**geom(64))
    fill(a, 40)
    path = ckpt.save_replay(str(tmp_path / "m"), a)
    assert path and path.endswith("_replay.npz")
    b = SharedReplay(**geom(64))
    assert ckpt.load_replay(str(tmp_path / "m"), b)
    assert b.size == 40
    ba = b.sample(16, np.random.default_rng(0))
    # restored rows carry the original contents
    assert set(np.unique(ba.reward)).issubset(set(np.arange(40.0)))


def test_shared_roundtrip_smaller_capacity_keeps_newest(tmp_path):
    a = SharedReplay(**geom(64))
    fill(a, 64)          # rewards 0..63 in slots 0..63
    fill(a, 10, seed=1)  # wrap: rewards 0..9 overwrite slots 0..9 (newest)
    ckpt.save_replay(str(tmp_path / "m"), a)
    b = SharedReplay(**geom(32))
    ckpt.load_replay(str(tmp_path / "m"), b)
    assert b.size == 32  # newest rows that fit
    # age order: newest 32 = first-pass rewards 42..63 + second-pass 0..9
    got = sorted(b._np_reward[:32].tolist())
    want = sorted(list(range(10)) + list(range(42, 64)))
    assert got == [float(x) for x in want]


def test_prioritized_roundtrip_preserves_leaves(tmp_path):
    a = PrioritizedReplay(**geom(64))
    fill(a, 50, priorities=True)
    leaves_a = a.sum_tree.get(np.arange(50))
    ckpt.save_replay(str(tmp_path / "m"), a)
    b = PrioritizedReplay(**geom(64))
    ckpt.load_replay(str(tmp_path / "m"), b)
    assert b.size == 50
    np.testing.assert_allclose(b.sum_tree.get(np.arange(50)), leaves_a)
    assert b.max_priority == a.max_priority
    # sampling works and IS weights are finite
    batch = b.sample(16, np.random.default_rng(0))
    assert np.isfinite(batch.weight).all()


def test_device_ring_roundtrip(tmp_path):
    from pytorch_distributed_tpu.memory.device_replay import DeviceReplay

    a = DeviceReplay(**geom(64))
    rng = np.random.default_rng(0)
    n = 40
    a.feed_chunk(Transition(
        state0=rng.integers(0, 255, size=(n, 4)).astype(np.uint8),
        action=rng.integers(0, 3, size=n).astype(np.int32),
        reward=np.arange(n, dtype=np.float32),
        gamma_n=np.full(n, 0.99, dtype=np.float32),
        state1=rng.integers(0, 255, size=(n, 4)).astype(np.uint8),
        terminal1=np.zeros(n, dtype=np.float32),
    ))
    ckpt.save_replay(str(tmp_path / "m"), a)
    b = DeviceReplay(**geom(64))
    ckpt.load_replay(str(tmp_path / "m"), b)
    assert b.size == n
    import jax

    st = jax.device_get(b.state)
    np.testing.assert_allclose(np.sort(np.asarray(st.reward)[:n]),
                               np.arange(n, dtype=np.float32))


def test_device_per_roundtrip_preserves_priorities(tmp_path):
    from pytorch_distributed_tpu.memory.device_per import DevicePerReplay
    import jax

    a = DevicePerReplay(**geom(64))
    rng = np.random.default_rng(0)
    n = 30
    a.feed_chunk(Transition(
        state0=rng.integers(0, 255, size=(n, 4)).astype(np.uint8),
        action=rng.integers(0, 3, size=n).astype(np.int32),
        reward=np.arange(n, dtype=np.float32),
        gamma_n=np.full(n, 0.99, dtype=np.float32),
        state1=rng.integers(0, 255, size=(n, 4)).astype(np.uint8),
        terminal1=np.zeros(n, dtype=np.float32),
    ))
    # make the leaves non-uniform, as after training write-backs
    from pytorch_distributed_tpu.memory.device_per import (
        per_update_priorities,
    )

    a.state = per_update_priorities(
        a.state, np.arange(n, dtype=np.int32),
        np.linspace(0.1, 3.0, n).astype(np.float32), alpha=a.alpha)
    leaves_a = np.asarray(jax.device_get(a.state.priority))[:n].copy()
    ckpt.save_replay(str(tmp_path / "m"), a)

    b = DevicePerReplay(**geom(64))
    ckpt.load_replay(str(tmp_path / "m"), b)
    assert b.size == n
    st = jax.device_get(b.state)
    np.testing.assert_allclose(np.asarray(st.priority)[:n], leaves_a,
                               rtol=1e-6)
    np.testing.assert_allclose(
        float(st.max_priority),
        float(jax.device_get(a.state.max_priority)), rtol=1e-6)


def test_missing_or_unsupported(tmp_path):
    assert ckpt.save_replay(str(tmp_path / "m"), object()) is None
    a = SharedReplay(**geom(8))
    assert not ckpt.load_replay(str(tmp_path / "nothing"), a)
    # a queue owner around a memory with no snapshot surface (e.g. the
    # sequence replay) skips cleanly instead of crashing the learner
    from pytorch_distributed_tpu.memory.feeder import QueueOwner

    class NoSnapshot:
        pass

    owner = QueueOwner(NoSnapshot())
    assert ckpt.save_replay(str(tmp_path / "m"), owner) is None
    # restoring a uniform-ring snapshot into a PER buffer falls back to
    # replay-once priorities instead of KeyError
    big = SharedReplay(**geom(16))
    fill(big, 12)
    ckpt.save_replay(str(tmp_path / "u"), big)
    per = PrioritizedReplay(**geom(16))
    assert ckpt.load_replay(str(tmp_path / "u"), per)
    assert per.size == 12
    batch = per.sample(8, np.random.default_rng(0))
    assert np.isfinite(batch.weight).all()


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_topology_resume_with_warm_replay(tmp_path):
    """End to end: run, stop, resume — the second run starts with the first
    run's replay AND train state (learner step continues)."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    common = dict(
        root_dir=str(tmp_path), num_actors=1, learn_start=64,
        batch_size=32, memory_size=2048, logger_freq=1, evaluator_freq=5,
        visualize=False, max_replay_ratio=16.0, early_stop=25,
        checkpoint_replay=True,
    )
    opt = build_options(config=1, steps=200, **common)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    # the run's final write is a committed checkpoint EPOCH binding train
    # state + replay + counters into one digest-valid triple
    info = ckpt.resolve_epoch(opt.model_name)
    assert info is not None and info.has_state and info.has_replay
    assert info.learner_step >= 200
    assert info.extras["replay_size"] > 0
    actor1 = info.extras["actor_step"]

    opt2 = build_options(config=1, steps=400, refs=opt.refs, **common)
    topo2 = runtime.train(opt2, backend="thread")
    # step counter resumed past the first run's 200 and reached 400
    assert topo2.clock.learner_step.value >= 400
    # clock counters carried across the resume (cumulative, no reset)
    info2 = ckpt.resolve_epoch(opt.model_name)
    assert info2.learner_step >= 400
    assert info2.extras["actor_step"] >= actor1
