import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.envs import (
    CartPoleEnv, FakeChainEnv, PendulumEnv, PongSimEnv,
)


def _params(config, **kw):
    return build_options(config=config, **kw).env_params


def test_fake_chain_optimal_rollout():
    env = FakeChainEnv(_params(1))
    obs = env.reset()
    assert obs.shape == (8,) and obs[0] == 1.0
    total, steps = 0.0, 0
    terminal = False
    while not terminal:
        obs, r, terminal, _ = env.step(1)
        total += r
        steps += 1
    assert steps == 7 and total == 1.0


def test_fake_chain_optimal_q_consistency():
    env = FakeChainEnv(_params(1))
    q = env.optimal_q(0.9)
    # Q(L-2, right) = immediate terminal reward
    assert q[-1, 1] == 1.0
    # bellman: Q(i, right) = gamma * max Q(i+1)
    for i in range(env.length - 2):
        assert q[i, 1] == pytest.approx(0.9 * q[i + 1].max())


def test_cartpole_runs_and_terminates():
    env = CartPoleEnv(_params(3))
    obs = env.reset()
    assert obs.shape == (4,) and obs.dtype == np.float32
    terminal, steps = False, 0
    while not terminal and steps < 1000:
        obs, r, terminal, _ = env.step(steps % 2)
        assert r == 1.0
        steps += 1
    assert terminal


def test_pendulum_reward_range_and_scaling():
    env = PendulumEnv(_params(2))
    obs = env.reset()
    assert obs.shape == (3,)
    assert np.isclose(np.linalg.norm(obs[:2]), 1.0, atol=1e-5)
    _, r, _, _ = env.step(np.array([0.5]))
    assert -17.0 < r <= 0.0
    # denormalize maps [-1,1] -> [-2,2]
    assert env.action_space.denormalize(np.array([1.0]))[0] == pytest.approx(2.0)
    assert env.action_space.denormalize(np.array([-1.0]))[0] == pytest.approx(-2.0)


def test_pendulum_episode_length():
    env = PendulumEnv(_params(2))
    env.reset()
    for i in range(200):
        _, _, terminal, _ = env.step(np.array([0.0]))
    assert terminal


def test_pong_sim_observation_contract():
    env = PongSimEnv(_params(4))
    obs = env.reset()
    assert obs.shape == (4, 84, 84)
    assert obs.dtype == np.uint8
    assert env.norm_val == 255.0
    assert env.action_space.n == 6
    obs, r, terminal, info = env.step(2)
    assert obs.shape == (4, 84, 84)
    assert "score" in info


def test_pong_sim_frame_stack_rolls():
    env = PongSimEnv(_params(4))
    obs0 = env.reset()
    obs1, *_ = env.step(0)
    # newest frame enters at the end of the stack
    np.testing.assert_array_equal(obs1[:-1][-1], obs0[-1])


def test_pong_sim_scoring_happens():
    env = PongSimEnv(_params(4))
    env.reset()
    rng = np.random.default_rng(0)
    rewards = []
    for _ in range(3000):
        _, r, terminal, _ = env.step(int(rng.integers(6)))
        rewards.append(r)
        if terminal:
            break
    # with random play the tracker opponent should score on us
    assert min(rewards) == -1.0


def test_pong_sim_tracker_policy_scores_points():
    # A perfect tracking policy should at least sometimes score
    env = PongSimEnv(_params(4))
    env.reset()
    got = 0.0
    for _ in range(5000):
        act = 2 if env.ball_y < env.player_y else 3
        _, r, terminal, _ = env.step(act)
        got += max(r, 0.0)
        if terminal:
            break
    assert got > 0


def test_early_stop_truncates():
    p = _params(1)
    p.early_stop = 5
    env = FakeChainEnv(p)
    env.reset()
    for _ in range(5):
        _, _, terminal, info = env.step(0)  # always-left never terminates naturally
    assert terminal and info.get("truncated")


def test_per_process_seed_diversity():
    a = PongSimEnv(_params(4), process_ind=0)
    b = PongSimEnv(_params(4), process_ind=1)
    assert a.seed != b.seed


def test_atari_gated_import_error():
    with pytest.raises(ImportError):
        from pytorch_distributed_tpu.envs.atari import AtariEnv
        AtariEnv(_params(0))


def test_vector_env_auto_reset_and_final_obs():
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import build_env_vector

    opt = build_options(config=1)
    v = build_env_vector(opt, process_ind=0, num_envs=3)
    v.train()
    obs = v.reset()
    assert obs.shape[0] == 3
    # drive env 0 to terminal (always-right on the 8-chain: 7 steps)
    for _ in range(7):
        nobs, r, term, infos = v.step([1, 0, 0])
    assert term[0] and not term[1] and not term[2]
    # terminal env auto-reset: returned obs is the reset obs, true terminal
    # frame rides in final_obs
    assert "final_obs" in infos[0]
    assert nobs[0][0] == 1.0            # reset to chain position 0
    assert infos[0]["final_obs"][-1] == 1.0  # terminal = right end
    # distinct seeds per env slot
    seeds = {e.seed for e in v.envs}
    assert len(seeds) == 3


def test_apex_epsilons_span_fleet():
    from pytorch_distributed_tpu.models.policies import (
        apex_epsilon, apex_epsilons,
    )

    # 2 actors x 4 envs == the 8-slot schedule of 8 plain actors
    a0 = apex_epsilons(0, 2, 4)
    a1 = apex_epsilons(1, 2, 4)
    flat = list(a0) + list(a1)
    want = [apex_epsilon(i, 8) for i in range(8)]
    import numpy as np
    np.testing.assert_allclose(flat, want, rtol=1e-6)
