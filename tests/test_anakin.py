"""The closed Anakin loop (ISSUE 12): co-located env fleet + learner.

The headline is the parity oracle: under a fixed seed and the
strict-alternation schedule, a co-located ``AnakinDriver`` run must be
bit-identical to the split-process ``actor_backend="device"`` path —
ring contents, PER priorities, and learner params after N steps —
because every XLA program involved is the SAME program the split path
dispatches (the fused rollout and the fused learner step); only the
host plumbing between them (spawn queue, pickle, chunk D2H/H2D)
vanishes.  The split leg here IS that plumbing: the chunk-emit rollout,
the real ``QueueFeeder`` -> mp queue -> ``DevicePerIngest.drain``
chain, and the learner's exact fused-step construction and key-stream
schedule, driven to the schedule the driver itself chose.

Geometry note: the split drain feeds the ring in ``chunk_sizes`` preset
multiples (smallest = 64) and parks the remainder pending — so the
parity geometry makes every dispatch's emission count a multiple of 64
((K - nstep) * N = 64, then K * N = 128); otherwise the split ring
would lag the co-located ring by the pending tail at each learn and
the sampled batches (hence params) would diverge for a reason that is
queue cadence, not semantics.

Satellites covered here: the duty-cycle scheduler + double-buffer swap
protocol (host logic, no dispatches), the no-actor-workers topology
contract, the transfer-audit-clean experience path, and the fleet
STATUS ``anakin`` panel block.
"""

import json
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.utils.experience import REPLAY_FIELDS


def _anakin_opts(tmp_path, **overrides):
    """Config-12 (pong-sim + device-per HBM ring) shrunk for CPU: the
    mlp head keeps compiles in seconds while exercising the real env
    fleet, ring scatter, PER write-back and fused learner step."""
    base = dict(
        root_dir=str(tmp_path), refs="anakin_t", num_actors=1,
        num_envs_per_actor=16, actor_backend="anakin", visualize=False,
        # dqn-mlp keeps compiles fast, but the mlp default ring schema
        # is float32 while the pong-sim device env emits uint8 frames —
        # pin the ring to uint8 (the config-12 cnn default) so the
        # split leg's ingest quarantine accepts the rollout's rows
        model_type="dqn-mlp", state_dtype="uint8",
        nstep=4, memory_size=256, learn_start=64,
        batch_size=32, steps=10 ** 6, early_stop=50,
        actor_freq=10 ** 9, learner_freq=10 ** 9,
        param_publish_freq=10 ** 9, checkpoint_freq=10 ** 9)
    base.update(overrides)
    opt = build_options(config=12, **base)
    opt.env_params.device_rollout_ticks = 8
    return opt


def _make_driver(opt):
    from pytorch_distributed_tpu.agents.anakin import AnakinDriver
    from pytorch_distributed_tpu.agents.clocks import (
        ActorStats, GlobalClock, LearnerStats,
    )
    from pytorch_distributed_tpu.agents.param_store import (
        ParamStore, make_flattener,
    )
    from pytorch_distributed_tpu.factory import (
        build_memory, build_model, init_params, probe_env,
    )

    spec = probe_env(opt)
    handles = build_memory(opt, spec)
    model = build_model(opt, spec)
    flat0, _ = make_flattener(init_params(opt, spec, model,
                                          seed=opt.seed))
    store = ParamStore(flat0.size)
    drv = AnakinDriver(opt, spec, handles.learner_side, store,
                       GlobalClock(), LearnerStats(),
                       actor_stats=ActorStats())
    return drv, handles, spec


class TestBackendGate:
    def test_eligible_config_resolves_anakin(self, tmp_path):
        from pytorch_distributed_tpu.factory import (
            anakin_active, resolve_actor_backend,
        )

        opt = _anakin_opts(tmp_path)
        assert resolve_actor_backend(opt) == "anakin"
        assert anakin_active(opt)

    def test_host_memory_downgrades_to_device(self, tmp_path):
        """anakin needs the HBM ring for the in-graph scatter; host
        replay falls back to the split-process device schedule."""
        from pytorch_distributed_tpu.factory import (
            anakin_active, resolve_actor_backend,
        )

        opt = build_options(
            config=4, root_dir=str(tmp_path), num_actors=1,
            actor_backend="anakin", visualize=False)
        with pytest.warns(UserWarning, match="anakin"):
            assert resolve_actor_backend(opt) == "device"
        assert not anakin_active(opt)

    def test_no_device_env_downgrades_all_the_way(self, tmp_path):
        """fake env has no device implementation: anakin -> device ->
        pipelined, warning at each gate."""
        from pytorch_distributed_tpu.factory import (
            anakin_active, resolve_actor_backend,
        )

        opt = build_options(
            config=1, root_dir=str(tmp_path), num_actors=1,
            memory_type="device", actor_backend="anakin",
            visualize=False)
        with pytest.warns(UserWarning):
            assert resolve_actor_backend(opt) == "pipelined"
        assert not anakin_active(opt)


class TestParityOracle:
    """Co-located vs split-process, one shared two-leg run."""

    DISPATCHES = 8  # strict alternation: 4 rollouts + 4 learner steps

    @pytest.fixture(scope="class")
    def run(self, tmp_path_factory):
        import jax

        from pytorch_distributed_tpu.agents.param_store import (
            make_flattener,
        )

        tmp = tmp_path_factory.mktemp("anakin_parity")

        # ---- leg A: the co-located driver, recording its schedule ----
        opt_a = _anakin_opts(tmp / "a")
        drv, handles_a, _spec = _make_driver(opt_a)
        assert drv.is_per and len(drv.rings) == 1
        schedule, fed_rows = [], 0
        for _ in range(self.DISPATCHES):
            if drv.want_rollout():
                st = drv.dispatch_rollout()
                fed_rows += int(st.fed)
                schedule.append("R")
            else:
                drv.dispatch_learn()
                schedule.append("L")
        ring_a = jax.device_get(drv.rings[0].state)
        flat_a, _ = make_flattener(jax.device_get(drv.state.params))
        handles_a.learner_side.close()

        # ---- leg B: the split-process path's exact pieces, driven to
        # the same schedule ----
        opt_b = _anakin_opts(tmp / "b", actor_backend="device")
        ring_b, flat_b, chunks = self._split_leg(opt_b, schedule)
        return dict(schedule=schedule, ring_a=ring_a, flat_a=flat_a,
                    ring_b=ring_b, flat_b=flat_b, chunks=chunks,
                    fed_rows=fed_rows)

    def _split_leg(self, opt, schedule):
        """The split-process ``actor_backend="device"`` path in one
        process: chunk-emit rollout -> QueueFeeder -> mp queue ->
        DevicePerIngest.drain -> the learner's fused step, with the
        actor acting on the train state's params each dispatch (the
        zero-staleness sync anakin gives by construction)."""
        import jax
        import jax.numpy as jnp

        from pytorch_distributed_tpu.agents.param_store import (
            make_flattener,
        )
        from pytorch_distributed_tpu.factory import (
            build_device_env, build_memory, build_model, init_params,
            build_train_state_and_step, probe_env,
        )
        from pytorch_distributed_tpu.models.policies import (
            apex_epsilons, build_fused_rollout, init_rollout_carry,
        )
        from pytorch_distributed_tpu.parallel.learner import (
            ShardedLearner,
        )
        from pytorch_distributed_tpu.parallel.mesh import make_mesh
        from pytorch_distributed_tpu.utils.experience import (
            Transition, make_prov,
        )
        from pytorch_distributed_tpu.utils.rngs import (
            np_rng, process_key,
        )

        ap = opt.agent_params
        pp = opt.parallel_params
        spec = probe_env(opt)
        ingest = build_memory(opt, spec).learner_side
        mesh = None
        if len(jax.devices()) > 1:
            mesh = make_mesh(pp.dp_size, pp.mp_size, pp.sp_size,
                             pp.ep_size, pp.pp_size)
        model = build_model(opt, spec)
        params = init_params(opt, spec, model, seed=opt.seed)
        state, step_fn = build_train_state_and_step(opt, spec, model,
                                                    params, mesh=mesh)
        learner = ShardedLearner(step_fn, mesh, donate=pp.donate)
        state = learner.place(state)
        ring = ingest.attach(mesh=mesh)
        fused = ring.build_fused_step(step_fn, ap.batch_size,
                                      donate=pp.donate,
                                      steps_per_call=1)
        device_key = jax.random.PRNGKey(
            np_rng(opt.seed, "learner", 0).integers(2 ** 31))
        key_buf, beta_dev, lstep = [], None, 0

        N = opt.env_params.num_envs_per_actor
        K = opt.env_params.device_rollout_ticks
        env = build_device_env(opt, 0, N)
        roll = build_fused_rollout(model.apply, env, nstep=ap.nstep,
                                   gamma=ap.gamma, rollout_ticks=K,
                                   emit="chunk")
        carry = init_rollout_carry(env, ap.nstep)
        base_key = jnp.asarray(process_key(opt.seed, "actor", 0))
        eps = jnp.asarray(apex_epsilons(0, 1, N, ap.eps, ap.eps_alpha),
                          jnp.float32)
        feeder = ingest.make_feeder()
        tick0 = jnp.int32(0)
        fed_expected, chunks = 0, []
        for kind in schedule:
            if kind == "R":
                carry, chunk = roll(state.params, carry, base_key,
                                    tick0, eps)
                tick0 = tick0 + K
                ch = jax.device_get(chunk)
                chunks.append(ch)
                valid = np.asarray(ch.valid)
                for k in range(K):
                    for j in range(N):
                        if not valid[k, j]:
                            continue
                        feeder.feed(Transition(
                            state0=ch.state0[k, j],
                            action=ch.action[k, j],
                            reward=ch.reward[k, j],
                            gamma_n=ch.gamma_n[k, j],
                            state1=ch.state1[k, j],
                            terminal1=ch.terminal1[k, j],
                            prov=make_prov(0, j, 0, lstep)), None)
                        fed_expected += 1
                feeder.flush()
            else:
                # the learner loop's drain cadence, held until the
                # queue's feeder thread has landed everything (in the
                # real topology the next loop iteration retries)
                deadline = time.monotonic() + 30.0
                while (ingest._fed_total < fed_expected
                       and time.monotonic() < deadline):
                    ingest.drain()
                    time.sleep(0.002)
                assert ingest._fed_total == fed_expected, \
                    "split drain never caught up — queue stall"
                if not key_buf:
                    keys = jax.random.split(device_key, 64 + 1)
                    device_key = keys[0]
                    key_buf = list(keys[1:])
                    beta_dev = jax.device_put(
                        np.float32(ring.beta(lstep)))
                state, ring.state, _m = fused(state, ring.state,
                                              key_buf.pop(), beta_dev)
                lstep += 1
        ring_b = jax.device_get(ring.state)
        flat_b, _ = make_flattener(jax.device_get(state.params))
        ingest.close()
        return ring_b, flat_b, chunks

    def test_schedule_is_strict_alternation_after_warmup(self, run):
        sched = "".join(run["schedule"])
        # min_fill = learn_start = 64 = the first dispatch's emissions
        assert sched == "RLRLRLRL"

    def test_ring_contents_bit_identical(self, run):
        a, b = run["ring_a"], run["ring_b"]
        for f in REPLAY_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                err_msg=f"ring field {f} diverged")
        assert int(a.pos) == int(b.pos)
        assert int(a.fill) == int(b.fill)

    def test_per_priorities_bit_identical(self, run):
        a, b = run["ring_a"], run["ring_b"]
        np.testing.assert_array_equal(np.asarray(a.priority),
                                      np.asarray(b.priority))
        assert float(a.max_priority) == float(b.max_priority)

    def test_learner_params_bit_identical(self, run):
        np.testing.assert_array_equal(run["flat_a"], run["flat_b"])

    def test_actions_bit_identical(self, run):
        """The split leg's chunk actions (every tick, valid or not)
        against the co-located ring's action column: emitted actions
        land row-for-row, so equality of the ring column + the env
        closure over actions covers the action stream."""
        acts = []
        for ch in run["chunks"]:
            valid = np.asarray(ch.valid)
            K, N = valid.shape
            for k in range(K):
                for j in range(N):
                    if valid[k, j]:
                        acts.append(np.asarray(ch.action[k, j]))
        assert len(acts) == run["fed_rows"]
        ring_act = np.asarray(run["ring_a"].action)
        cap = ring_act.shape[0]
        assert len(acts) >= cap  # the run wraps: every slot rewritten
        exp = np.zeros_like(ring_act)
        for i, a in enumerate(acts):  # later writes win, like the ring
            exp[i % cap] = a
        np.testing.assert_array_equal(ring_act, exp)
        assert int(run["ring_a"].fill) == cap

    def test_provenance_scattered_in_graph(self, run):
        """Written rows carry in-graph stamps (actor 0, their env
        slot), not the -1 sentinel — the ISSUE-8 columns survive the
        co-located scatter."""
        prov = np.asarray(run["ring_a"].prov)
        fill = int(run["ring_a"].fill)
        assert (prov[:fill, 0] == 0).all()          # actor_id
        assert (prov[:fill, 1] >= 0).all()          # env_slot
        assert (prov[:fill, 1] < 16).all()


class TestDutyCycleScheduler:
    """Host-side scheduler logic: no dispatches, just the driver's
    bookkeeping — constructing a driver compiles nothing (the jit
    wrappers trace on first call and the perf plane is off)."""

    @pytest.fixture(scope="class")
    def drv(self, tmp_path_factory):
        opt = _anakin_opts(tmp_path_factory.mktemp("anakin_sched"),
                           double_buffer=True, learn_start=32)
        d, handles, _ = _make_driver(opt)
        yield d
        handles.learner_side.close()

    def _reset(self, d):
        d._fill = [0 for _ in d.rings]
        d._fresh = 0
        d.sample_ix = d.write_ix = 0
        d.frames = 0
        d.lstep = d.lstep0 = 0
        d._last_was_rollout = False

    def test_double_buffer_geometry(self, drv):
        assert len(drv.rings) == 2
        assert drv.rings[0].capacity == drv.rings[1].capacity == 128
        assert drv.min_fill == 32

    def test_warmup_forces_rollouts(self, drv):
        self._reset(drv)
        assert drv.want_rollout()
        drv._fill[0] = drv.min_fill - 1
        assert drv.want_rollout()

    def test_cold_start_split_then_swap_on_fresh(self, drv):
        self._reset(drv)
        # cold start: write half detaches once it holds min_fill
        drv._fill[0] = drv.min_fill
        drv._maybe_swap()
        assert (drv.sample_ix, drv.write_ix) == (0, 1)
        # fresh rows below the bar: no swap
        drv._fresh = drv.min_fill - 1
        drv._maybe_swap()
        assert (drv.sample_ix, drv.write_ix) == (0, 1)
        # bar reached: halves swap and the fresh counter re-arms
        drv._fresh = drv.min_fill
        drv._maybe_swap()
        assert (drv.sample_ix, drv.write_ix) == (1, 0)
        assert drv._fresh == 0

    def test_sample_half_never_the_write_half_after_detach(self, drv):
        self._reset(drv)
        drv._fill[0] = drv.min_fill
        for _ in range(8):
            drv._fresh = drv.min_fill
            drv._maybe_swap()
            assert drv.sample_ix != drv.write_ix

    def test_strict_alternation_when_ratio_zero(self, drv):
        self._reset(drv)
        drv._fill[0] = drv.min_fill
        drv._maybe_swap()
        assert drv.an.rollout_ratio == 0
        drv._last_was_rollout = True
        assert not drv.want_rollout()
        drv._last_was_rollout = False
        assert drv.want_rollout()

    def test_rollout_ratio_setpoint(self, drv):
        import dataclasses

        self._reset(drv)
        drv._fill[0] = drv.min_fill
        drv._maybe_swap()
        drv.an = dataclasses.replace(drv.an, rollout_ratio=128.0)
        try:
            drv.lstep = drv.lstep0 + 2  # 2 updates -> setpoint 256
            drv.frames = 255
            assert drv.want_rollout()
            drv.frames = 256
            assert not drv.want_rollout()
        finally:
            drv.an = dataclasses.replace(drv.an, rollout_ratio=0.0)

    def test_env_knob_override(self, monkeypatch):
        from pytorch_distributed_tpu.agents.anakin import resolve_anakin
        from pytorch_distributed_tpu.config import AnakinParams

        monkeypatch.setenv("TPU_APEX_ANAKIN_ROLLOUT_RATIO", "64")
        monkeypatch.setenv("TPU_APEX_ANAKIN_DOUBLE_BUFFER", "1")
        monkeypatch.setenv("TPU_APEX_ANAKIN_MIN_FILL", "7")
        ap = AnakinParams()
        out = resolve_anakin(ap)
        assert (out.rollout_ratio, out.double_buffer, out.min_fill) \
            == (64.0, True, 7)
        assert ap.rollout_ratio == 0.0  # input never mutated


class TestResume:
    def test_resume_seeds_cumulative_frames(self, tmp_path):
        """Duty-cycle counters ride the checkpoint: a resumed driver
        restores the CUMULATIVE frames count next to the restored
        lstep/lstep0 — a zeroed counter would read as a frames deficit
        of (lstep - lstep0) * rollout_ratio and flood rollout-only
        (zero updates, zero stats cadences) until it caught up."""
        opt = _anakin_opts(tmp_path, num_envs_per_actor=4,
                           learn_start=8, batch_size=8,
                           rollout_ratio=64.0)
        opt.env_params.device_rollout_ticks = 8
        drv, handles, _ = _make_driver(opt)
        try:
            for _ in range(4):
                if drv.want_rollout():
                    drv.dispatch_rollout()
                else:
                    drv.dispatch_learn()
            frames, lstep = drv.frames, drv.lstep
            assert frames > 0 and lstep > drv.lstep0
            deficit = (lstep - drv.lstep0) * drv.an.rollout_ratio \
                - frames
            drv._save_epoch()
        finally:
            drv.writer.close()
            handles.learner_side.close()

        drv2, handles2, _ = _make_driver(opt)
        try:
            assert drv2.lstep == lstep
            assert drv2.frames == frames, \
                "resume zeroed the duty-cycle frames counter"
            # the setpoint deficit survives the restart unchanged — a
            # zeroed counter would inflate it by every frame ever
            # collected (the rollout-only flood)
            assert (drv2.lstep - drv2.lstep0) * drv2.an.rollout_ratio \
                - drv2.frames == deficit
        finally:
            drv2.writer.close()
            handles2.learner_side.close()


class TestTopologyContract:
    def test_no_actor_workers_spawn(self, tmp_path):
        """anakin_active topologies carry zero actor worker specs and
        no actor slots on the watchdog board — the learner IS the
        fleet."""
        from pytorch_distributed_tpu.runtime import Topology

        opt = _anakin_opts(tmp_path, num_actors=4)
        topo = Topology(opt)
        try:
            assert topo.anakin
            roles = [s[0] for s in topo._worker_specs()]
            assert "actor" not in roles
            assert "logger" in roles
        finally:
            topo.handles.learner_side.close()

    def test_split_topology_keeps_actor_workers(self, tmp_path):
        from pytorch_distributed_tpu.runtime import Topology

        opt = _anakin_opts(tmp_path, num_actors=2,
                           actor_backend="device")
        topo = Topology(opt)
        try:
            assert not topo.anakin
            roles = [s[0] for s in topo._worker_specs()]
            assert roles.count("actor") == 2
        finally:
            topo.handles.learner_side.close()


class TestAuditAndPerfPlane:
    def test_dispatches_transfer_free_and_mfu_combined(self, tmp_path,
                                                       monkeypatch):
        """The acceptance bar's transfer claim, in-process: with the
        perf plane + transfer audit on, a rollout->learn->rollout
        cycle stages ZERO implicit host->device transfers (the
        explicit 12-byte prov device_put is control plane and passes
        by definition), and the drained MFU sums the update- and
        frame-denominated programs."""
        monkeypatch.setenv("TPU_APEX_PERF", "1")
        monkeypatch.setenv("TPU_APEX_PERF_TRANSFER_AUDIT", "1")
        from pytorch_distributed_tpu.utils import perf

        perf.reset()
        try:
            opt = _anakin_opts(tmp_path, num_envs_per_actor=4,
                               learn_start=8, batch_size=8)
            opt.env_params.device_rollout_ticks = 4
            drv, handles, _ = _make_driver(opt)
            assert drv.audit is not None
            drv.perf.drain()  # anchor the rate window
            for _ in range(6):
                if drv.want_rollout():
                    drv.dispatch_rollout()
                else:
                    drv.dispatch_learn()
            assert drv.audit.total == 0, \
                f"implicit transfers on the experience path: " \
                f"{drv.audit.sites}"
            # the zero-copy scatter shows up in the ingest's host
            # accounting (fleet STATUS replay_size/fill would read a
            # busy ring as empty otherwise)
            assert handles.learner_side.size > 0
            assert drv.replay_fill() > 0
            assert drv.perf.flops_per_update and \
                drv.perf.flops_per_update > 0
            assert drv.perf.flops_per_frame and \
                drv.perf.flops_per_frame > 0
            rows = drv.perf.drain(step=drv.lstep)
            assert rows["learner/achieved_flops_per_s"] == pytest.approx(
                rows["learner/updates_per_s"]
                * drv.perf.flops_per_update
                + rows["learner/env_frames_per_s"]
                * drv.perf.flops_per_frame, rel=1e-6)
            assert "anakin_rollout" in drv.perf.retraces._fns
            handles.learner_side.close()
        finally:
            perf.reset()


class TestFleetStatusAnakinBlock:
    def test_health_snapshot_carries_anakin_block(self, tmp_path,
                                                  monkeypatch):
        """ISSUE 12 satellite: the gateway STATUS payload carries the
        co-located loop's vitals — fleet_top renders them and the
        --json consumers read them verbatim."""
        import json as _json

        monkeypatch.setenv("TPU_APEX_PERF", "1")
        from pytorch_distributed_tpu.fleet import FleetTopology
        from pytorch_distributed_tpu.utils import perf

        perf.reset()
        try:
            opt = _anakin_opts(tmp_path)
            topo = FleetTopology(opt, local_actors=0, port=0)
            try:
                assert topo.anakin
                mon = perf.get_monitor("learner")
                mon.note_updates(10)
                mon.drain()
                mon.set_gauge("anakin/duty_cycle", 0.44)
                mon.set_gauge("anakin/rollout_frames_per_s", 1234.0)
                mon.set_gauge("anakin/replay_fill", 0.5)
                mon.drain()
                h = topo._health_snapshot()
                blk = h["anakin"]
                assert blk["backend"] == "anakin"
                assert blk["duty_cycle"] == pytest.approx(0.44)
                assert blk["rollout_frames_per_s"] == pytest.approx(
                    1234.0)
                assert blk["replay_fill"] == pytest.approx(0.5)
                assert "actors" not in h or not h.get("actors")
                _json.dumps(h)  # the --json path must serialize
                from tools.fleet_top import anakin_line, render

                line = anakin_line(h)
                assert line and "duty 44%" in line
                assert "anakin:" in render(h)
            finally:
                topo.gateway.close()
        finally:
            perf.reset()


# ---------------------------------------------------------------------------
# acceptance: the full co-located topology, live (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(420)
class TestAnakinTopologyAcceptance:
    def test_full_topology_closed_loop(self, tmp_path, monkeypatch):
        """ISSUE 12 acceptance drill: the REAL anakin topology — fleet
        gateway + logger + the co-located learner/env-fleet loop — runs
        a bounded training session end to end.  Verified live: the
        STATUS ``anakin`` block appears mid-run with a real duty cycle
        and zero actor slots; verified post-run: the duty-cycle
        telemetry landed in the metrics stream, the logger's actor
        curves flowed without any actor worker existing, and a complete
        checkpoint epoch committed (the preemption/resume surface the
        driver shares with the split learner)."""
        monkeypatch.setenv("TPU_APEX_PERF", "1")
        monkeypatch.setenv("TPU_APEX_PERF_PEAK_FLOPS", "1e12")
        from pytorch_distributed_tpu.fleet import FleetTopology
        from pytorch_distributed_tpu.parallel.dcn import fetch_status
        from pytorch_distributed_tpu.utils import perf
        from pytorch_distributed_tpu.utils.checkpoint import resolve_epoch
        from pytorch_distributed_tpu.utils.metrics import read_scalars

        perf.reset()
        try:
            opt = _anakin_opts(
                tmp_path, steps=160, max_seconds=240.0,
                learner_freq=10, actor_freq=64, logger_freq=1,
                checkpoint_freq=50, param_publish_freq=40,
                evaluator_nepisodes=0)
            topo = FleetTopology(opt, local_actors=0, port=0)
            done = threading.Event()

            def run():
                try:
                    topo.run(backend="thread")
                finally:
                    done.set()

            t = threading.Thread(target=run, daemon=True)
            t.start()
            addr = ("127.0.0.1", topo.port)
            try:
                status, blk = None, None
                deadline = time.monotonic() + 300
                while time.monotonic() < deadline and not done.is_set():
                    try:
                        status = fetch_status(addr, timeout=5.0)
                    except (ConnectionError, OSError):
                        status = None
                    blk = (status or {}).get("anakin")
                    if blk and blk.get("duty_cycle") is not None:
                        break
                    time.sleep(0.25)
                assert blk, "anakin block never appeared in STATUS"
                assert blk["backend"] == "anakin"
                assert 0.0 < blk["duty_cycle"] < 1.0
                assert blk["rollout_frames_per_s"] > 0
                assert not status.get("actors"), \
                    "actor slots exist on an anakin topology"
                json.dumps(status)
            finally:
                t.join(360)
            assert not t.is_alive()

            rows = read_scalars(opt.log_dir)
            by_tag = {}
            for r in rows:
                if "value" in r:
                    by_tag.setdefault(r["tag"], []).append(r["value"])
            for tag in ("anakin/duty_cycle", "anakin/rollout_frames_per_s",
                        "anakin/replay_fill", "learner/updates_per_s"):
                assert tag in by_tag, \
                    f"{tag} missing (have {sorted(by_tag)[:30]}...)"
            assert any(0.0 < v < 1.0 for v in by_tag["anakin/duty_cycle"])
            assert max(by_tag["anakin/replay_fill"]) > 0
            # the logger's rollout curves flowed from the co-located
            # fleet (no actor worker exists to push them)
            assert "actor/total_nframes" in by_tag
            assert sum(by_tag["actor/total_nframes"]) > 0
            # a complete epoch committed on the checkpoint cadence
            epoch = resolve_epoch(opt.model_name)
            assert epoch is not None and epoch.learner_step >= 50
        finally:
            perf.reset()
