"""Child process for tests/test_multihost.py: joins a 2-process
jax.distributed CPU cluster via parallel/mesh.init_multihost, builds the
global mesh, and runs one cross-process reduction.

Run: python _multihost_child.py <coordinator> <num_processes> <process_id>
Prints MULTIHOST_OK <total> on success.  Must configure platform before
first jax use (this image's sitecustomize pre-imports jax pinned to a
hardware platform)."""

import os
import re
import sys


def main() -> None:
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu.parallel.mesh import init_multihost, make_mesh

    init_multihost(coordinator_address=coordinator,
                   num_processes=num_processes, process_id=process_id)

    assert jax.process_index() == process_id
    assert len(jax.local_devices()) == 2
    assert jax.device_count() == 2 * num_processes, jax.device_count()

    # the same mesh code a pod uses, now spanning both processes' devices
    mesh = make_mesh(dp_size=2 * num_processes)

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    # each process contributes rows valued (process_id + 1); the jitted
    # sum over the dp-sharded global array forces a cross-process
    # all-reduce through the distributed runtime
    local = np.full((2, 3), float(process_id + 1), np.float32)
    arr = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("dp"))
    total = jax.jit(jnp.sum,
                    out_shardings=NamedSharding(mesh, P()))(arr)
    expected = 3.0 * 2 * sum(range(1, num_processes + 1))
    np.testing.assert_allclose(float(total), expected)

    # the learner-spans-hosts leg: the production DQN train step jitted
    # over the global mesh — params replicated on every host, the batch
    # dp-sharded across hosts, XLA closing the gradients with a
    # cross-process all-reduce
    from pytorch_distributed_tpu.models import DqnMlpModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )
    from pytorch_distributed_tpu.utils.experience import Batch

    model = DqnMlpModel(action_space=3, hidden_dim=32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6)))
    state = init_train_state(params, make_optimizer(lr=1e-3))
    step = build_dqn_train_step(model.apply, make_optimizer(lr=1e-3),
                                enable_double=True, target_model_update=10)

    def replicate(x):
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, P())

    def shard_rows(x):
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, P("dp"))

    gstate = jax.tree_util.tree_map(replicate, state)
    rng = np.random.default_rng(7)  # same on every process; rows split
    B_local = 4
    lo = process_id * B_local
    full = rng.normal(size=(num_processes * B_local, 6)).astype(np.float32)
    acts = rng.integers(0, 3, size=num_processes * B_local).astype(np.int32)
    rew = rng.normal(size=num_processes * B_local).astype(np.float32)
    batch = Batch(
        state0=shard_rows(full[lo:lo + B_local]),
        action=shard_rows(acts[lo:lo + B_local]),
        reward=shard_rows(rew[lo:lo + B_local]),
        gamma_n=shard_rows(np.full(B_local, 0.95, np.float32)),
        state1=shard_rows(full[lo:lo + B_local] + 0.1),
        terminal1=shard_rows(np.zeros(B_local, np.float32)),
        weight=shard_rows(np.ones(B_local, np.float32)),
        index=shard_rows(np.arange(lo, lo + B_local, dtype=np.int32)),
    )
    fn = jax.jit(step)
    for _ in range(2):
        gstate, metrics, _td = fn(gstate, batch)
    jax.block_until_ready(gstate.params)
    assert int(jax.device_get(gstate.step)) == 2
    loss = float(jax.device_get(metrics["learner/critic_loss"]))
    assert np.isfinite(loss)
    # every process must see the identical post-all-reduce loss
    losses = multihost_utils.process_allgather(np.float32(loss))
    np.testing.assert_allclose(losses, losses[0])

    multihost_utils.sync_global_devices("test_done")
    print(f"MULTIHOST_OK {float(total)} loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
