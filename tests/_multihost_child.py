"""Child process for tests/test_multihost.py: joins a 2-process
jax.distributed CPU cluster via parallel/mesh.init_multihost, builds the
global mesh, and runs one cross-process reduction.

Run: python _multihost_child.py <coordinator> <num_processes> <process_id>
Prints MULTIHOST_OK <total> on success.  Must configure platform before
first jax use (this image's sitecustomize pre-imports jax pinned to a
hardware platform)."""

import os
import re
import sys


def main() -> None:
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu.parallel.mesh import init_multihost, make_mesh

    init_multihost(coordinator_address=coordinator,
                   num_processes=num_processes, process_id=process_id)

    assert jax.process_index() == process_id
    assert len(jax.local_devices()) == 2
    assert jax.device_count() == 2 * num_processes, jax.device_count()

    # the same mesh code a pod uses, now spanning both processes' devices
    mesh = make_mesh(dp_size=2 * num_processes)

    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    # each process contributes rows valued (process_id + 1); the jitted
    # sum over the dp-sharded global array forces a cross-process
    # all-reduce through the distributed runtime
    local = np.full((2, 3), float(process_id + 1), np.float32)
    arr = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("dp"))
    total = jax.jit(jnp.sum,
                    out_shardings=NamedSharding(mesh, P()))(arr)
    expected = 3.0 * 2 * sum(range(1, num_processes + 1))
    np.testing.assert_allclose(float(total), expected)
    multihost_utils.sync_global_devices("test_done")
    print(f"MULTIHOST_OK {float(total)}", flush=True)


if __name__ == "__main__":
    main()
