import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.utils.helpers import (
    hard_update, periodic_update, soft_update, update_target,
)


def _tree(val):
    return {"w": jnp.full((3,), val), "b": jnp.asarray(val)}


def test_soft_update():
    out = soft_update(_tree(0.0), _tree(1.0), tau=0.1)
    np.testing.assert_allclose(out["w"], 0.1, atol=1e-7)


def test_hard_update():
    out = hard_update(_tree(0.0), _tree(5.0))
    np.testing.assert_allclose(out["b"], 5.0)


def test_periodic_update_gates_on_step():
    tgt, onl = _tree(0.0), _tree(7.0)
    hit = periodic_update(tgt, onl, jnp.asarray(500), period=250)
    miss = periodic_update(tgt, onl, jnp.asarray(501), period=250)
    np.testing.assert_allclose(hit["w"], 7.0)
    np.testing.assert_allclose(miss["w"], 0.0)


def test_update_target_dispatch():
    # tau-style (<1) vs periodic (>=1), reference utils/helpers.py:19-25
    soft = update_target(_tree(0.0), _tree(1.0), jnp.asarray(3), 1e-3)
    np.testing.assert_allclose(soft["b"], 1e-3, atol=1e-9)
    hard = update_target(_tree(0.0), _tree(1.0), jnp.asarray(250), 250)
    np.testing.assert_allclose(hard["b"], 1.0)


def test_update_target_jits():
    f = jax.jit(lambda t, o, s: update_target(t, o, s, 250))
    out = f(_tree(0.0), _tree(2.0), jnp.asarray(0))
    np.testing.assert_allclose(out["w"], 2.0)
