"""Run-budget and shutdown paths added for the time-boxed bench: the
max_seconds wall-clock budget, the no-evaluator switch, and the
stop-aware feeder that keeps teardown from deadlocking."""

import multiprocessing as mp
import queue
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_max_seconds_ends_run_like_steps_budget(tmp_path):
    """A steps budget far beyond reach + a few-second wall budget: the
    topology must return on the clock, not hang."""
    from pytorch_distributed_tpu import runtime

    opt = build_options(
        1, root_dir=str(tmp_path), num_actors=1, steps=10 ** 9,
        max_seconds=8.0, memory_size=1024, batch_size=16, learn_start=16,
        visualize=False, evaluator_freq=10 ** 6)
    t0 = time.monotonic()
    topo = runtime.train(opt, backend="thread")
    assert time.monotonic() - t0 < 120.0  # compile + 8s budget + join
    assert topo.clock.stop.is_set()
    assert topo.clock.learner_step.value < 10 ** 9


def test_evaluator_nepisodes_zero_skips_evaluator_worker(tmp_path):
    from pytorch_distributed_tpu.runtime import Topology

    opt = build_options(1, root_dir=str(tmp_path), num_actors=2,
                        evaluator_nepisodes=0, visualize=False)
    topo = Topology(opt)
    roles = [role for role, _, _ in topo._worker_specs()]
    assert "evaluator" not in roles
    assert roles.count("actor") == 2
    # the logger's end-of-run drain gates on this handshake
    assert topo.evaluator_stats.done.value == 1

    opt2 = build_options(1, root_dir=str(tmp_path), num_actors=2,
                         visualize=False)
    topo2 = Topology(opt2)
    assert "evaluator" in [r for r, _, _ in topo2._worker_specs()]


class TestStopAwareFeeder:
    def _transition(self):
        from pytorch_distributed_tpu.utils.experience import Transition

        z = np.zeros(2, np.float32)
        return Transition(state0=z, action=np.int32(0),
                          reward=np.float32(0.0), gamma_n=np.float32(0.9),
                          state1=z, terminal1=np.float32(0.0))

    def test_flush_aborts_on_stop_instead_of_blocking(self):
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        q = mp.get_context("spawn").Queue(1)
        f = QueueFeeder(q, chunk=1)
        stop = mp.get_context("spawn").Event()
        f.set_stop(stop)
        f.feed(self._transition())  # fills the 1-slot queue
        time.sleep(0.2)  # let the mp feeder thread push it into the pipe

        # queue full, nobody draining: a flush must wait only until stop
        f._buf = [(self._transition(), None)]
        done = threading.Event()

        def blocked_flush():
            f.flush()
            done.set()

        t = threading.Thread(target=blocked_flush, daemon=True)
        t.start()
        assert not done.wait(0.6), "flush returned while queue still full"
        stop.set()
        assert done.wait(5.0), "flush did not abort on stop"
        assert f._buf == []  # dropped, not retained
        f.close()

    def test_plain_put_for_sinks_without_timeout(self):
        """Duck-typed sinks (the DCN _ChunkSink) have put(items) only —
        the stop-aware branch must not pass timeout= to them."""
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        class Sink:
            def __init__(self):
                self.items = []

            def put(self, items):  # no timeout kwarg
                self.items.append(items)

        sink = Sink()
        f = QueueFeeder(sink, chunk=1)
        f.set_stop(mp.get_context("spawn").Event())
        f.feed(self._transition())
        assert len(sink.items) == 1

    def test_clone_carries_stop(self):
        from pytorch_distributed_tpu.memory.feeder import QueueFeeder

        f = QueueFeeder(queue.Queue(4), chunk=2)
        stop = threading.Event()
        f.set_stop(stop)
        c = f.clone()
        assert c._stop is stop and c._timeout_put == f._timeout_put
