"""Training health sentinel drills (utils/health.py + the ladder wiring).

Fast tier: unit drills for every rung — the in-jit finite guard (params
provably bit-unchanged across a skipped step), the PER write-back
suppression, the anomaly detector, ingest validation/quarantine on all
three boundaries (QueueOwner, DeviceReplayIngest, DcnGateway), the
NaN-vs-None priority wire fix, malformed-frame rejection, the rollback
checkpoint machinery, the ProgressBoard, and an in-process learner run
that diverges, rolls back to its last good epoch and completes.

Slow tier (excluded from tier-1): full process-topology drills — a hung
actor SIGKILLed and respawned by the watchdog, and the end-to-end chaos
acceptance run mixing poison_chunk / poison_grad / hang in one topology.
"""

from __future__ import annotations

import json
import io
import os
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.utils import flight_recorder, health, tracing
from pytorch_distributed_tpu.utils.experience import Batch, Transition


@pytest.fixture(autouse=True)
def _isolate(tmp_path, monkeypatch):
    """Each test gets its own quarantine/blackbox home and a clean
    registry; fault-plane envs never leak between tests."""
    health.reset()
    flight_recorder.reset()
    flight_recorder.configure(str(tmp_path))
    for var in ("FEEDER_FAULTS", "LEARNER_FAULTS", "ACTOR_FAULTS",
                "TPU_APEX_QUARANTINE"):
        monkeypatch.delenv(var, raising=False)
    yield
    health.reset()
    flight_recorder.reset()


def _transition(reward=0.5, state=None, action=0, priority=None,
                dtype=np.float32, shape=(4,)):
    s = (np.zeros(shape, dtype) if state is None
         else np.asarray(state, dtype))
    return (Transition(state0=s, action=np.int32(action),
                       reward=np.float32(reward),
                       gamma_n=np.float32(0.99),
                       state1=s.copy(), terminal1=np.float32(0.0)),
            priority)


# ---------------------------------------------------------------------------
# in-jit finite guard
# ---------------------------------------------------------------------------

class TestFiniteGuard:
    def _setup(self):
        import jax

        from pytorch_distributed_tpu.models import DqnMlpModel
        from pytorch_distributed_tpu.ops.losses import (
            build_dqn_train_step, init_train_state, make_optimizer,
        )

        model = DqnMlpModel(action_space=3, hidden_dim=16)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4), np.float32))
        tx = make_optimizer(1e-3)
        state = init_train_state(params, tx)
        step = jax.jit(build_dqn_train_step(model.apply, tx))
        return state, step

    def _batch(self, reward):
        B = 4
        rng = np.random.default_rng(0)
        return Batch(
            state0=rng.normal(size=(B, 4)).astype(np.float32),
            action=rng.integers(0, 3, B).astype(np.int32),
            reward=np.full(B, reward, np.float32),
            gamma_n=np.full(B, 0.99, np.float32),
            state1=rng.normal(size=(B, 4)).astype(np.float32),
            terminal1=np.zeros(B, np.float32),
            weight=np.ones(B, np.float32),
            index=np.arange(B, dtype=np.int32))

    def test_nonfinite_step_skipped_params_bit_unchanged(self):
        import jax

        state, step = self._setup()
        state, m, _ = step(state, self._batch(1.0))
        assert float(m[health.SKIPPED_KEY]) == 0.0
        before = [np.asarray(x) for x in jax.tree_util.tree_leaves(state)]
        state2, m2, td2 = step(state, self._batch(np.nan))
        assert float(m2[health.SKIPPED_KEY]) == 1.0
        # the raw loss stays visible (the anomaly detector wants it)...
        assert not np.isfinite(float(m2["learner/critic_loss"]))
        # ...but params, opt state AND the step counter are bit-unchanged
        after = [np.asarray(x) for x in jax.tree_util.tree_leaves(state2)]
        for a, b in zip(before, after):
            assert np.array_equal(a, b, equal_nan=True)
        # TD zeroed so an unaware write-back can't scatter NaN priorities
        assert float(np.abs(np.asarray(td2)).sum()) == 0.0

    def test_recovers_after_skip(self):
        state, step = self._setup()
        state, _, _ = step(state, self._batch(1.0))
        state, _, _ = step(state, self._batch(np.nan))
        state, m, _ = step(state, self._batch(1.0))
        assert float(m[health.SKIPPED_KEY]) == 0.0
        assert int(state.step) == 2  # skipped step never counted

    def test_guard_off_passes_nan_through(self):
        import jax

        from pytorch_distributed_tpu.models import DqnMlpModel
        from pytorch_distributed_tpu.ops.losses import (
            build_dqn_train_step, init_train_state, make_optimizer,
        )

        model = DqnMlpModel(action_space=3, hidden_dim=16)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 4), np.float32))
        tx = make_optimizer(1e-3)
        state = init_train_state(params, tx)
        step = jax.jit(build_dqn_train_step(model.apply, tx, guard=False))
        state, m, _ = step(state, self._batch(np.nan))
        assert health.SKIPPED_KEY not in m
        leaves = jax.tree_util.tree_leaves(state.params)
        assert not all(np.isfinite(np.asarray(x)).all() for x in leaves)

    def test_reduce_scan_metrics_sums_skip_counter(self):
        import jax.numpy as jnp

        stacked = {"learner/critic_loss": jnp.asarray([1.0, 2.0, 3.0]),
                   health.SKIPPED_KEY: jnp.asarray([1.0, 0.0, 1.0])}
        out = health.reduce_scan_metrics(stacked)
        assert float(out["learner/critic_loss"]) == 3.0
        assert float(out[health.SKIPPED_KEY]) == 2.0

    def test_per_writeback_suppressed_on_skip(self):
        """A guarded step that skips must leave the fused PER ring's
        priorities bit-unchanged (its zeroed TD would otherwise crush
        every sampled row to epsilon priority)."""
        import jax

        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay,
        )

        ring = DevicePerReplay(capacity=32, state_shape=(4,),
                               state_dtype=np.float32)
        rng = np.random.default_rng(1)
        C = 32
        ring.feed_chunk(Transition(
            state0=rng.normal(size=(C, 4)).astype(np.float32),
            action=rng.integers(0, 3, C).astype(np.int32),
            reward=rng.normal(size=C).astype(np.float32),
            gamma_n=np.full(C, 0.99, np.float32),
            state1=rng.normal(size=(C, 4)).astype(np.float32),
            terminal1=np.zeros(C, np.float32)))

        def raw_step(bad):
            def step(ts, batch):
                td = jnp_full = np.nan if bad else 1.0
                import jax.numpy as jnp

                td_abs = jnp.full(batch.reward.shape[0], jnp_full,
                                  jnp.float32)
                metrics = {"learner/critic_loss": jnp.sum(td_abs)}
                return {"w": ts["w"] + 1.0}, metrics, td_abs
            return health.finite_guard(step)

        ts = {"w": np.float32(0.0)}
        fused_bad = ring.build_fused_step(raw_step(bad=True), 8,
                                          donate=False)
        before = np.asarray(jax.device_get(ring.state.priority))
        key = jax.random.PRNGKey(0)
        ts2, rs2, m = fused_bad(ts, ring.state, key, np.float32(0.4))
        assert float(m[health.SKIPPED_KEY]) == 1.0
        assert np.array_equal(np.asarray(jax.device_get(rs2.priority)),
                              before)
        assert float(ts2["w"]) == 0.0  # train state passed through too
        fused_ok = ring.build_fused_step(raw_step(bad=False), 8,
                                         donate=False)
        ts3, rs3, m3 = fused_ok(ts, ring.state, key, np.float32(0.4))
        assert float(m3[health.SKIPPED_KEY]) == 0.0
        assert not np.array_equal(
            np.asarray(jax.device_get(rs3.priority)), before)


# ---------------------------------------------------------------------------
# anomaly detector
# ---------------------------------------------------------------------------

class TestAnomalyDetector:
    def test_steady_loss_never_trips(self):
        d = health.AnomalyDetector(zmax=6.0, threshold=2)
        rng = np.random.default_rng(0)
        for _ in range(50):
            assert d.observe(loss=1.0 + 0.01 * rng.normal(),
                             grad_norm=0.5) == []
        assert not d.should_rollback()

    def test_loss_spike_and_streak(self):
        d = health.AnomalyDetector(zmax=6.0, threshold=2)
        for _ in range(20):
            d.observe(loss=1.0, grad_norm=0.5)
        # EWMA variance of a constant is ~0; floor makes any jump trip
        assert "loss_spike" in d.observe(loss=500.0, grad_norm=0.5)
        assert not d.should_rollback()  # streak 1 < threshold 2
        d.observe(loss=500.0, grad_norm=0.5)
        assert d.should_rollback()
        d.observe(loss=1.0, grad_norm=0.5)  # healthy window resets
        assert not d.should_rollback()

    def test_grad_spike_and_nonfinite(self):
        d = health.AnomalyDetector(grad_spike=10.0, threshold=1)
        for _ in range(20):
            d.observe(loss=1.0, grad_norm=1.0)
        assert "grad_spike" in d.observe(loss=1.0, grad_norm=100.0)
        assert "nonfinite" in d.observe(loss=float("nan"), grad_norm=1.0)
        assert "skipped" in d.observe(loss=1.0, grad_norm=1.0, skipped=3)

    def test_spikes_do_not_poison_baseline(self):
        d = health.AnomalyDetector(grad_spike=10.0, threshold=99)
        for _ in range(20):
            d.observe(grad_norm=1.0)
        for _ in range(5):  # a sustained spike keeps tripping: the
            # anomalous readings never fold into their own baseline
            assert "grad_spike" in d.observe(grad_norm=100.0)

    def test_priority_collapse_and_reset(self):
        d = health.AnomalyDetector(threshold=1)
        assert "priority_collapse" in d.observe(priority_mass=0.0,
                                                replay_rows=100)
        assert d.should_rollback()
        d.reset()
        assert not d.should_rollback()
        assert d.observe(priority_mass=5.0, replay_rows=100) == []


# ---------------------------------------------------------------------------
# ingest validation + quarantine stores
# ---------------------------------------------------------------------------

class TestChunkValidator:
    def test_clean_items_pass_as_same_object(self):
        v = health.ChunkValidator()
        items = tracing.TracedChunk([_transition(), _transition(1.0, priority=2.0)])
        out, bad = v.filter(items)
        assert out is items and bad == []

    def test_nonfinite_scalars_rejected(self):
        v = health.ChunkValidator()
        out, bad = v.filter([_transition(), _transition(np.nan)])
        assert len(out) == 1 and len(bad) == 1
        assert "reward" in bad[0][2]

    def test_nan_obs_rejected_for_float_states(self):
        v = health.ChunkValidator()
        s = np.array([1.0, np.nan, 0.0, 0.0], np.float32)
        out, bad = v.filter([_transition(state=s)])
        assert not out and "state0" in bad[0][2]

    def test_uint8_states_skip_the_scan(self):
        v = health.ChunkValidator()
        out, bad = v.filter(
            [_transition(state=np.zeros((2, 2), np.uint8),
                         dtype=np.uint8, shape=(2, 2))])
        assert out and not bad

    def test_priority_garbage_rejected(self):
        v = health.ChunkValidator()
        out, bad = v.filter([_transition(priority=float("nan")),
                             _transition(priority=-1.0),
                             _transition(priority=3.0)])
        assert len(out) == 1 and len(bad) == 2

    def test_shape_and_dtype_drift_rejected(self):
        v = health.ChunkValidator(state_shape=(4,), state_dtype=np.float32)
        out, bad = v.filter([
            _transition(),
            _transition(shape=(5,)),                      # shape drift
            _transition(dtype=np.float64),                # dtype drift
        ])
        assert len(out) == 1 and len(bad) == 2
        assert "shape" in bad[0][2] and "dtype" in bad[1][2]

    def test_first_seen_schema_latches(self):
        v = health.ChunkValidator()
        out, bad = v.filter([_transition(shape=(4,))])
        assert not bad
        out, bad = v.filter([_transition(shape=(8,))])
        assert bad and "shape" in bad[0][2]

    def test_action_range(self):
        v = health.ChunkValidator(num_actions=4)
        out, bad = v.filter([_transition(action=3), _transition(action=7)])
        assert len(out) == 1 and "range" in bad[0][2]

    # -- Segment rows (R2D2 sequence topologies) ------------------------

    def _segment(self, T=4, reward_nan_at=None, obs_shape=(5, 3)):
        from pytorch_distributed_tpu.memory.sequence_replay import Segment

        reward = np.zeros(T, np.float32)
        if reward_nan_at is not None:
            reward[reward_nan_at] = np.nan
        return Segment(
            obs=np.zeros(obs_shape, np.float32),
            action=np.zeros(T, np.int32), reward=reward,
            terminal=np.zeros(T, np.float32),
            mask=np.ones(T, np.float32),
            c0=np.zeros(2, np.float32), h0=np.zeros(2, np.float32))

    def test_segment_rows_validate_instead_of_crashing(self):
        """Regression (found driving config 13 under ISSUE 9): the
        validator scalar-checked Segment.reward — a (T,) array — and
        raised ValueError on the learner's FIRST drain of every
        sequence topology with quarantine active.  The per-step
        state_shape a SequenceReplay advertises must also never be
        compared against the segment's whole-window obs."""
        v = health.ChunkValidator(state_shape=(3,),
                                  state_dtype=np.float32)
        out, bad = v.filter([(self._segment(), 1.0),
                             (self._segment(), None)])
        assert len(out) == 2 and bad == []

    def test_segment_nonfinite_and_drift_rejected(self):
        v = health.ChunkValidator()
        out, bad = v.filter([
            (self._segment(), 1.0),
            (self._segment(reward_nan_at=2), 1.0),   # NaN reward step
            (self._segment(obs_shape=(6, 3)), 1.0),  # window drift
        ])
        assert len(out) == 1 and len(bad) == 2
        assert "reward" in bad[0][2] and "shape" in bad[1][2]


class TestQuarantineStore:
    def test_writes_npz_with_reason_and_trace(self, tmp_path):
        st = health.get_quarantine("test-src")
        t, p = _transition(np.nan)
        path = st.put([(t, p, "non-finite reward")], trace_id=0xabc)
        assert path and os.path.exists(path)
        with np.load(path) as z:
            assert "non-finite reward" in str(z["reason"][0])
            assert z["trace_id"][0] == tracing.format_trace_id(0xabc)
            assert np.isnan(z["reward"][0])
        assert health.quarantine_counts() == {"test-src": 1}

    def test_file_budget_bounds_disk_not_counting(self):
        st = health.QuarantineStore("bounded", max_files=2)
        for _ in range(5):
            st.put([(*_transition(np.nan), "r")])
        assert st.files == 2 and st.count == 5

    def test_segment_rows_quarantine_without_crashing(self):
        """Companion to the validator segment fix: put() must dump the
        SEGMENT schema, not getattr the six transition columns (that
        crashed the drain on the first rejected segment)."""
        from pytorch_distributed_tpu.memory.sequence_replay import (
            Segment,
        )

        seg = Segment(obs=np.zeros((5, 3), np.float32),
                      action=np.zeros(4, np.int32),
                      reward=np.full(4, np.nan, np.float32),
                      terminal=np.zeros(4, np.float32),
                      mask=np.ones(4, np.float32),
                      c0=np.zeros(2, np.float32),
                      h0=np.zeros(2, np.float32))
        st = health.get_quarantine("seq-src")
        path = st.put([(seg, 1.0, "non-finite reward")])
        assert path and os.path.exists(path)
        with np.load(path) as z:
            assert z["obs"].shape == (1, 5, 3)
            assert np.isnan(z["reward"]).any()
            assert "state0" not in z.files

    def test_shape_drifted_offenders_still_quarantine(self):
        st = health.get_quarantine("drift")
        bad = [( _transition(shape=(3,))[0], None, "shape drift"),
               (_transition(shape=(9,))[0], None, "shape drift")]
        path = st.put(bad)
        assert path and os.path.exists(path)


class TestIngestBoundaries:
    def _owner(self):
        from pytorch_distributed_tpu.memory.feeder import QueueOwner

        class Rec:
            def __init__(self):
                self.items = []

            def feed(self, t, p):
                self.items.append((t, p))

        rec = Rec()
        return QueueOwner(rec), rec

    def test_queue_owner_drain_quarantines(self):
        owner, rec = self._owner()
        f = owner.make_feeder(chunk=2)
        f.feed(*_transition(0.1))
        f.feed(*_transition(0.2))          # clean chunk latches schema
        f.feed(*_transition(np.nan))
        f.feed(*_transition(0.3))          # mixed chunk: 1 bad, 1 good
        time.sleep(0.2)  # spawn queue feeder thread latency
        while owner.drain():
            pass
        assert len(rec.items) == 3
        assert all(np.isfinite(t.reward) for t, _ in rec.items)
        assert health.quarantine_counts() == {"feeder-local": 1}

    def test_quarantine_kill_switch(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_QUARANTINE", "0")
        owner, rec = self._owner()
        f = owner.make_feeder(chunk=1)
        f.feed(*_transition(np.nan))
        time.sleep(0.2)
        while owner.drain():
            pass
        assert len(rec.items) == 1  # pre-sentinel behaviour restored
        assert health.quarantine_counts() == {}

    def test_poison_chunk_verb_poisons_then_quarantined(self, monkeypatch):
        monkeypatch.setenv("FEEDER_FAULTS", "poison_chunk@1")
        owner, rec = self._owner()
        f = owner.make_feeder(chunk=2)
        for i in range(4):  # flush 0 clean, flush 1 poisoned
            f.feed(*_transition(0.1 * (i + 1)))
        time.sleep(0.2)
        while owner.drain():
            pass
        assert len(rec.items) == 2
        assert health.quarantine_counts() == {"feeder-local": 2}

    def test_device_ingest_quarantines_shape_drift(self):
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplayIngest,
        )

        ing = DeviceReplayIngest(capacity=64, state_shape=(4,),
                                 state_dtype=np.float32, chunk_size=2)
        ing.attach(mesh=None)
        f = ing.make_feeder(chunk=2)
        f.feed(*_transition(0.1))
        f.feed(*_transition(np.nan))       # caught by finiteness
        f.feed(*_transition(0.2, shape=(7,)))  # would crash np.stack
        f.feed(*_transition(0.3))
        time.sleep(0.2)
        ing.drain()
        snap = ing.snapshot()
        assert len(snap["reward"]) == 2
        assert np.isfinite(snap["reward"]).all()
        assert health.quarantine_counts() == {"feeder-device": 2}


# ---------------------------------------------------------------------------
# DCN wire: priority validity, malformed frames, gateway quarantine
# ---------------------------------------------------------------------------

class TestWirePriorityValidity:
    def test_none_vs_nan_round_trip(self):
        from pytorch_distributed_tpu.parallel.dcn import (
            decode_chunk, encode_chunk,
        )

        items = [_transition(priority=None), _transition(priority=1.5),
                 _transition(priority=float("nan"))]
        out = decode_chunk(encode_chunk(items))
        assert out[0][1] is None
        assert out[1][1] == 1.5
        # the regression this satellite fixes: a genuine NaN priority
        # must survive as NaN (to be quarantined), never decode as None
        assert out[2][1] is not None and np.isnan(out[2][1])

    def test_sentinel_era_frames_still_decode(self):
        """Old peers without the validity column: NaN meant None."""
        from pytorch_distributed_tpu.parallel.dcn import (
            _FIELDS, decode_chunk, encode_chunk,
        )

        payload = encode_chunk([_transition(priority=None),
                                _transition(priority=2.0)])
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files if k != "priority_ok"}
        buf = io.BytesIO()
        np.savez(buf, **cols)
        out = decode_chunk(buf.getvalue())
        assert out[0][1] is None and out[1][1] == 2.0
        assert set(_FIELDS) <= set(cols)


class TestMalformedFrames:
    def _payload(self, mutate):
        from pytorch_distributed_tpu.parallel.dcn import encode_chunk

        payload = encode_chunk([_transition(0.1), _transition(0.2)])
        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
        mutate(cols)
        buf = io.BytesIO()
        np.savez(buf, **cols)
        return buf.getvalue()

    def test_truncated_column_rejected(self):
        from pytorch_distributed_tpu.parallel.dcn import decode_chunk

        def truncate(cols):
            cols["reward"] = cols["reward"][:1]
        with pytest.raises(ValueError, match="length"):
            decode_chunk(self._payload(truncate))

    def test_missing_column_rejected(self):
        from pytorch_distributed_tpu.parallel.dcn import decode_chunk

        def drop(cols):
            del cols["gamma_n"]
        with pytest.raises(ValueError, match="missing"):
            decode_chunk(self._payload(drop))

    def test_wrong_dtype_rejected(self):
        from pytorch_distributed_tpu.parallel.dcn import decode_chunk

        def stringify(cols):
            cols["reward"] = np.array(["a", "b"])
        with pytest.raises(ValueError, match="not numeric"):
            decode_chunk(self._payload(stringify))

    def test_garbage_bytes_stay_on_connection_path(self):
        from pytorch_distributed_tpu.parallel.dcn import decode_chunk

        with pytest.raises(ConnectionError):
            decode_chunk(b"\x00garbage-not-a-zip")


class _GatewayPlane:
    """Minimal live gateway + sink, no jax/topology."""

    def __init__(self):
        from pytorch_distributed_tpu.agents.clocks import (
            ActorStats, GlobalClock,
        )
        from pytorch_distributed_tpu.agents.param_store import ParamStore
        from pytorch_distributed_tpu.parallel.dcn import DcnGateway

        self.delivered = []
        self.clock = GlobalClock()
        store = ParamStore(4)
        store.publish(np.zeros(4, np.float32))
        self.gw = DcnGateway(store, self.clock, ActorStats(),
                             put_chunk=self.delivered.append,
                             host="127.0.0.1", port=0)

    def close(self):
        self.gw.close()


class TestGatewayIngest:
    def test_poisoned_chunk_quarantined_per_slot(self):
        from pytorch_distributed_tpu.parallel.dcn import DcnClient

        plane = _GatewayPlane()
        try:
            client = DcnClient(("127.0.0.1", plane.gw.port),
                               process_ind=2, heartbeat_interval=0.0)
            client.send_chunk([_transition(0.5)])
            client.send_chunk([_transition(np.nan),
                               _transition(0.7)])
            flat = [t for chunk in plane.delivered for t, _p in chunk]
            assert len(flat) == 2
            assert all(np.isfinite(t.reward) for t in flat)
            snap = plane.gw.status_snapshot()
            assert snap["quarantined"] == {"slot2": 1}
            assert plane.gw.chunks_in == 2  # session never dropped
            client.close()
        finally:
            plane.close()

    def test_malformed_frame_rejected_with_ack_session_survives(self):
        import socket
        import struct

        from pytorch_distributed_tpu.parallel.dcn import (
            T_CLOCK, T_EXP, T_HELLO, T_PING, _recv_frame, _send_frame,
            encode_chunk,
        )

        plane = _GatewayPlane()
        try:
            sock = socket.create_connection(("127.0.0.1", plane.gw.port),
                                            timeout=5.0)
            sock.settimeout(5.0)
            _send_frame(sock, T_HELLO, json.dumps(
                {"role": "actor", "process_ind": 0,
                 "incarnation": 1}).encode())
            assert _recv_frame(sock)[0] == T_CLOCK
            # well-framed savez with a truncated column: schema reject
            payload = encode_chunk([_transition(0.1), _transition(0.2)])
            with np.load(io.BytesIO(payload)) as z:
                cols = {k: z[k] for k in z.files}
            cols["priority"] = cols["priority"][:1]
            buf = io.BytesIO()
            np.savez(buf, **cols)
            _send_frame(sock, T_EXP, buf.getvalue())
            rtype, _ = _recv_frame(sock)  # acked, NOT disconnected
            assert rtype == T_CLOCK
            _send_frame(sock, T_PING, b"")
            assert _recv_frame(sock)[0] == T_CLOCK  # session alive
            assert plane.gw.frames_rejected == 1
            assert plane.delivered == []
            snap = plane.gw.status_snapshot()
            assert snap["frames_rejected"] == 1
            sock.close()
        finally:
            plane.close()


# ---------------------------------------------------------------------------
# rollback machinery (checkpoint tier)
# ---------------------------------------------------------------------------

class TestRollbackCheckpointMachinery:
    def _save(self, model_name, step, extras=None):
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        return ckpt.save_epoch(model_name, state=None,
                               extras=dict(learner_step=step,
                                           **(extras or {})),
                               retain=10)

    def test_resolve_skips_rolled_back_and_respects_before(self, tmp_path):
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        name = str(tmp_path / "run")
        for step in (10, 20, 30):
            self._save(name, step)
        info = ckpt.resolve_epoch(name)
        assert info.epoch == 2 and info.learner_step == 30
        ckpt.mark_rolled_back(info.path, to_epoch=1, reason="drill")
        info = ckpt.resolve_epoch(name)
        assert info.epoch == 1 and info.learner_step == 20
        info = ckpt.resolve_epoch(name, before=1)
        assert info.epoch == 0
        assert ckpt.resolve_epoch(name, before=0) is None

    def test_fsck_reports_rolled_back_cleanly(self, tmp_path):
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        name = str(tmp_path / "run")
        for step in (10, 20, 30):
            self._save(name, step)
        root = ckpt.ckpt_root(name)
        # a rollback to epoch 0 fences epochs 1 and 2; the run then
        # saves epoch 3 with a REGRESSED learner_step — legal, because
        # the overtaken epochs are marked
        for k in (1, 2):
            ckpt.mark_rolled_back(os.path.join(root, f"epoch_{k}"),
                                  to_epoch=0, reason="drill")
        self._save(name, 15, extras={"rollbacks": 1})
        rep = ckpt.fsck(root)
        assert rep["violations"] == []
        assert rep["rolled_back"] == 2
        assert rep["newest_complete"] == 3

    def test_fsck_flags_unmarked_step_regression(self, tmp_path):
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        name = str(tmp_path / "run")
        self._save(name, 30)
        self._save(name, 10)  # regression with NO rollback marker: a lie
        rep = ckpt.fsck(ckpt.ckpt_root(name))
        assert any("regressed" in v for v in rep["violations"])

    def test_gc_never_lets_rolled_back_crowd_out_good(self, tmp_path):
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        name = str(tmp_path / "run")
        for step in (10, 20, 30):
            self._save(name, step)
        root = ckpt.ckpt_root(name)
        for k in (1, 2):
            ckpt.mark_rolled_back(os.path.join(root, f"epoch_{k}"))
        ckpt.gc_epochs(root, retain=1)
        # the only GOOD epoch (0) must survive retain=1 even though two
        # newer (fenced) epochs exist
        info = ckpt.resolve_epoch(name)
        assert info is not None and info.epoch == 0

    def test_ckpt_fsck_cli_exits_clean_on_rollback_root(self, tmp_path):
        import importlib

        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        fsck_cli = importlib.import_module("tools.ckpt_fsck")
        name = str(tmp_path / "run")
        for step in (10, 20):
            self._save(name, step)
        root = ckpt.ckpt_root(name)
        ckpt.mark_rolled_back(os.path.join(root, "epoch_1"), to_epoch=0)
        self._save(name, 12, extras={"rollbacks": 1})
        assert fsck_cli.main([root]) == 0


# ---------------------------------------------------------------------------
# progress board (hang watchdog core)
# ---------------------------------------------------------------------------

class TestProgressBoard:
    def test_never_started_is_never_hung(self):
        from pytorch_distributed_tpu.utils.supervision import ProgressBoard

        b = ProgressBoard(["actor-0"])
        assert b.hung(0.001) == []

    def test_grace_covers_first_compile_then_deadline_applies(self):
        from pytorch_distributed_tpu.utils.supervision import ProgressBoard

        b = ProgressBoard(["actor-0", "actor-1"])
        b.note_start("actor-0")
        b.note_start("actor-1")
        b.bump("actor-1")
        now = time.time() + 0.5
        # 0 never bumped: deadline+grace (0.3+1.0) not yet reached;
        # 1 bumped: plain deadline 0.3 exceeded
        assert b.hung(0.3, grace=1.0, now=now) == ["actor-1"]
        now = time.time() + 2.0
        assert set(b.hung(0.3, grace=1.0, now=now)) == {"actor-0",
                                                        "actor-1"}

    def test_bump_clears_and_respawn_restarts_grace(self):
        from pytorch_distributed_tpu.utils.supervision import ProgressBoard

        b = ProgressBoard(["w"])
        b.note_start("w")
        b.bump("w", 3)
        assert b.marks("w") == 3
        assert b.hung(10.0) == []
        b.note_start("w")  # respawn: marks reset, grace window restarts
        assert b.marks("w") == 0

    def test_disabled_deadline(self):
        from pytorch_distributed_tpu.utils.supervision import ProgressBoard

        b = ProgressBoard(["w"])
        b.note_start("w")
        assert b.hung(0.0, now=time.time() + 999) == []


# ---------------------------------------------------------------------------
# the full detection -> containment -> recovery ladder, in process
# ---------------------------------------------------------------------------

class TestLearnerSentinel:
    @pytest.mark.timeout(240)
    def test_divergence_rolls_back_to_last_good_epoch(self, tmp_path,
                                                      monkeypatch):
        """Thread-backend topology on the chain MDP: poison_grad NaNs
        every update for several stats windows; the guard skips them
        all (no NaN ever reaches Adam), the anomaly streak trips, the
        learner rolls back to its last committed epoch in-process and
        the run completes with exit 0 semantics — final params finite,
        exactly one rollback consumed, blackbox stamped."""
        from pytorch_distributed_tpu import runtime
        from pytorch_distributed_tpu.config import build_options
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        spec = ",".join(f"poison_grad@{i}" for i in range(30, 54))
        monkeypatch.setenv("LEARNER_FAULTS", spec)
        opt = build_options(
            1, root_dir=str(tmp_path), refs="health_rb", seed=7,
            num_actors=1, steps=90, learn_start=16, batch_size=8,
            checkpoint_freq=25, learner_freq=8, evaluator_nepisodes=0,
            visualize=False, anomaly_threshold=2, max_rollbacks=3)
        topo = runtime.train(opt, backend="thread")
        assert topo.clock.rollbacks.value == 1
        assert topo.clock.skipped_steps.value >= 1
        # the fenced (overtaken) epochs carry markers; the root fscks
        # clean — a resumed run can never step back onto diverged params
        rep = ckpt.fsck(ckpt.ckpt_root(opt.model_name))
        assert rep["violations"] == []
        # blackbox records the rollback event
        bb = os.path.join(opt.log_dir, "blackbox", "learner.jsonl")
        assert os.path.exists(bb)
        with open(bb) as f:
            kinds = [json.loads(line).get("kind") for line in f]
        assert "rollback" in kinds

    @pytest.mark.timeout(240)
    def test_rollback_budget_exhaustion_is_fatal(self, tmp_path,
                                                 monkeypatch):
        """Sustained divergence with max_rollbacks=0 must escalate to a
        fatal learner exit, never loop forever."""
        from pytorch_distributed_tpu import runtime
        from pytorch_distributed_tpu.config import build_options

        spec = ",".join(f"poison_grad@{i}" for i in range(30, 90))
        monkeypatch.setenv("LEARNER_FAULTS", spec)
        opt = build_options(
            1, root_dir=str(tmp_path), refs="health_fatal", seed=7,
            num_actors=1, steps=200, learn_start=16, batch_size=8,
            checkpoint_freq=25, learner_freq=8, evaluator_nepisodes=0,
            visualize=False, anomaly_threshold=2, max_rollbacks=0)
        with pytest.raises(RuntimeError, match="health"):
            runtime.train(opt, backend="thread")


# ---------------------------------------------------------------------------
# slow full-topology drills (excluded from tier-1)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.timeout(420)
def test_hang_watchdog_kills_and_respawns_actor(tmp_path, monkeypatch):
    """Process topology: actor-0 stops progressing at tick 40 without
    exiting (hang@40); the watchdog must SIGKILL it, classify EXIT_HUNG,
    respawn from the RestartBudget, and the run completes.

    Every respawned incarnation re-fires its deterministic hang@40 (the
    schedule is per-process), exactly like a worker with a deterministic
    stall bug — so the run is sized to finish on the LAST incarnation
    before it reaches tick 40 again: replay-ratio pacing needs
    2*steps = 96 actor ticks = 40 + 40 + 16, i.e. two watchdog kills
    inside a 3-restart budget."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    monkeypatch.setenv("ACTOR_FAULTS", "hang@40")
    monkeypatch.setenv("TPU_APEX_HEALTH_HANG_DEADLINE", "5")
    monkeypatch.setenv("TPU_APEX_HEALTH_HANG_GRACE", "120")
    opt = build_options(
        1, root_dir=str(tmp_path), refs="health_hang", seed=3,
        num_actors=1, steps=48, learn_start=16, batch_size=8,
        learner_freq=16, evaluator_nepisodes=0, visualize=False,
        max_replay_ratio=4.0)
    topo = runtime.train(opt, backend="process")
    assert 1 <= topo.hang_kills <= 3
    assert int(topo.clock.learner_step.value) >= 48
    bb = os.path.join(opt.log_dir, "blackbox")
    assert os.path.isdir(bb)  # the kill dumped post-mortems first


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_e2e_chaos_drill_poison_and_hang(tmp_path, monkeypatch):
    """The acceptance drill: one process-backend PER run with
    poison_chunk@N (feeder), poison_grad@M (learner) and hang@K (actor)
    all scripted.  The run must complete cleanly with: quarantine files
    written, replay verifiably free of non-finite values, the poisoned
    update skipped, at most one rollback consumed, and the hung actor
    respawned within its RestartBudget."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    # flush 1 = the actor's second 16-transition chunk (~tick 37) —
    # safely before its hang@60 stops the feed
    monkeypatch.setenv("FEEDER_FAULTS", "poison_chunk@1")
    monkeypatch.setenv("LEARNER_FAULTS", "poison_grad@60")
    monkeypatch.setenv("ACTOR_FAULTS", "hang@60")
    monkeypatch.setenv("TPU_APEX_HEALTH_HANG_DEADLINE", "5")
    monkeypatch.setenv("TPU_APEX_HEALTH_HANG_GRACE", "120")
    # sized like the hang drill: pacing needs 2*steps = 160 actor ticks
    # = 60 + 60 + 40, so the final incarnation finishes the run before
    # re-firing ITS hang@60 — two watchdog kills inside the budget
    opt = build_options(
        1, root_dir=str(tmp_path), refs="health_chaos", seed=11,
        memory_type="prioritized",
        num_actors=1, steps=80, learn_start=16, batch_size=8,
        learner_freq=16, evaluator_nepisodes=0, visualize=False,
        max_replay_ratio=4.0)
    topo = runtime.train(opt, backend="process")
    # run completed (exit-0 semantics): the clock reached the budget
    assert int(topo.clock.learner_step.value) >= 80
    # hung actor detected, killed, respawned within budget
    assert 1 <= topo.hang_kills <= 3
    # the poisoned update was skipped in-graph
    assert int(topo.clock.skipped_steps.value) >= 1
    # at most one rollback consumed (none expected: one skip is not a
    # sustained anomaly)
    assert int(topo.clock.rollbacks.value) <= 1
    # quarantine file written (learner-side ingest boundary)
    qdir = os.path.join(opt.log_dir, "quarantine")
    files = os.listdir(qdir)
    assert any(f.startswith("feeder-local") for f in files)
    with np.load(os.path.join(qdir, sorted(files)[0])) as z:
        assert "reason" in z and "trace_id" in z
    # replay is bit-clean: no non-finite value anywhere (the wrapped
    # memory directly — the owner's ingest queue is closed post-run)
    snap = topo.handles.learner_side.memory.snapshot()
    assert len(snap["reward"]) > 0
    for key in ("state0", "reward", "gamma_n", "state1", "terminal1"):
        assert np.isfinite(np.asarray(snap[key], np.float64)).all(), key
    assert np.isfinite(np.asarray(snap["leaf_priority"])).all()
