"""Mission control (ISSUE 10): fleet metrics aggregation, the
SLO/alert state machine, OpenMetrics exposition, the T_METRICS push
path with clock-offset alignment, and the end-to-end acceptance drill
— a seeded chaos_soak learner stall whose absence alert fires, shows
in ``fleet_top --json``, lands on the ``tools/timeline.py`` incident
timeline, and resolves after recovery."""

import io
import json
import os
import socket
import subprocess
import sys
import threading
import time
from contextlib import redirect_stdout

import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import AlertParams, MetricsParams
from pytorch_distributed_tpu.parallel.dcn import (
    DcnGateway, fetch_status, push_metrics,
)
from pytorch_distributed_tpu.utils import flight_recorder, telemetry
from pytorch_distributed_tpu.utils.metrics import (
    MetricsWriter, ScalarsTail, is_scalar_row, read_scalars,
)
from pytorch_distributed_tpu.utils.telemetry import (
    AlertEngine, FleetMetrics, MetricsPusher, MissionControl,
    OpenMetricsServer, SeriesRing, openmetrics_text, parse_rule,
    parse_rules,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("TPU_APEX_METRICS", "TPU_APEX_ALERT_RULES"):
        monkeypatch.delenv(var, raising=False)
    flight_recorder.reset()
    yield
    flight_recorder.reset()


def _row(tag, value, wall, role="learner", step=0):
    return {"tag": tag, "value": float(value), "wall": float(wall),
            "step": step, "role": role}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# the series ring: bounded retention tiers
# ---------------------------------------------------------------------------

class TestSeriesRing:
    def test_raw_ring_evicts_by_span_and_count(self):
        ring = SeriesRing(raw_span=10.0, raw_points=64)
        t0 = 1000.0
        for i in range(200):
            ring.append(t0 + i * 0.5, float(i))
        pts = ring.recent(500)
        assert len(pts) <= 64
        newest = pts[-1][0]
        assert all(newest - w <= 10.0 for w, _v in pts)
        assert ring.latest() == (t0 + 199 * 0.5, 199.0)
        assert ring.appended == 200

    def test_downsample_tiers_extend_past_raw(self):
        """History older than the raw span survives as bucket means —
        the memory stays O(tier spans) while a window query still
        reaches back hours."""
        ring = SeriesRing(raw_span=30.0, raw_points=64,
                          tiers=((10.0, 3600.0),))
        t0 = 5000.0
        for i in range(120):  # 10 minutes of 5 s-spaced points
            ring.append(t0 + i * 5.0, float(i))
        # raw only covers the last 30 s; a 10-minute window must reach
        # into the 10 s-bucket tier
        win = ring.window(600.0, now=t0 + 600.0)
        assert len(win) > 7  # far more than the raw tail alone
        walls = [w for w, _v in win]
        assert walls == sorted(walls)
        assert min(walls) < t0 + 595.0 - 30.0  # pre-raw history present

    def test_out_of_order_append_folds_not_crashes(self):
        ring = SeriesRing(raw_span=60.0)
        ring.append(100.0, 1.0)
        ring.append(90.0, 2.0)  # merged-role interleave
        assert ring.appended == 2


# ---------------------------------------------------------------------------
# aggregator
# ---------------------------------------------------------------------------

class TestFleetMetrics:
    def test_ingest_filters_non_scalar_rows(self):
        m = FleetMetrics(MetricsParams(enabled=True))
        now = time.time()
        n = m.ingest([
            _row("a/b", 1.0, now),
            {"tag": "h", "kind": "histogram", "p50": 1.0, "wall": now},
            {"tag": "s", "kind": "span", "value": 2.0, "wall": now},
            {"no": "tag"},
            _row("a/b", 2.0, now + 1),
        ])
        assert n == 2
        assert m.latest("a/b") == (now + 1, 2.0)
        assert m.tags() == ["a/b"]

    def test_per_role_series_merge_on_read(self):
        m = FleetMetrics(MetricsParams(enabled=True))
        now = time.time()
        m.ingest([_row("t", 1.0, now, role="actor-0"),
                  _row("t", 2.0, now + 1, role="actor-1")])
        assert m.latest("t") == (now + 1, 2.0)
        assert len(m.window("t", 60.0, now=now + 2)) == 2
        blk = m.series_block(["t"])
        assert blk["t"]["latest"] == 2.0
        assert len(blk["t"]["points"]) == 2

    def test_series_cap_counts_dropped_never_silent(self):
        m = FleetMetrics(MetricsParams(enabled=True, max_series=2))
        now = time.time()
        m.ingest([_row(f"tag{i}", 1.0, now) for i in range(5)])
        assert len(m.tags()) == 2
        assert m.series_dropped == 3

    def test_remote_offset_shifts_walls(self):
        m = FleetMetrics(MetricsParams(enabled=True))
        now = time.time()
        m.ingest([_row("t", 1.0, now - 2.5)], offset=2.5)
        wall, _v = m.latest("t")
        assert wall == pytest.approx(now, abs=1e-6)


# ---------------------------------------------------------------------------
# rule DSL
# ---------------------------------------------------------------------------

class TestRuleParsing:
    def test_threshold_with_dwell(self):
        r = parse_rule("slow: learner/updates_per_s < 0.5 for 30s")
        assert (r.name, r.kind, r.op, r.value, r.for_s) == (
            "slow", "threshold", "<", 0.5, 30.0)

    def test_absence_and_duration_units(self):
        r = parse_rule("stall: learner/updates_per_s absent 2m")
        assert r.kind == "absence" and r.window_s == 120.0
        assert parse_rule("x: t absent 500ms").window_s == 0.5
        assert parse_rule("x: t absent 45").window_s == 45.0

    def test_burn_rate(self):
        r = parse_rule("burn: data/staleness_p50 > 100 frac 0.5 "
                       "over 300s")
        assert (r.kind, r.frac, r.window_s, r.value) == (
            "burn_rate", 0.5, 300.0, 100.0)

    def test_name_defaults_from_tag(self):
        assert parse_rule("replay/priority_ess_frac < 0.02").name == \
            "replay_priority_ess_frac"

    def test_semicolon_string_and_duplicates(self):
        rules = parse_rules("a: t absent 1s; b: t > 5 for 2s")
        assert [r.name for r in rules] == ["a", "b"]
        with pytest.raises(ValueError, match="duplicate"):
            parse_rules("a: t absent 1s; a: t > 5")

    def test_garbage_raises(self):
        with pytest.raises(ValueError, match="unparseable"):
            parse_rule("what even is this")
        with pytest.raises(ValueError, match="frac"):
            parse_rule("x: t > 1 frac 7 over 10s")
        with pytest.raises(ValueError, match="unparseable"):
            parse_rule("x: t > +e+.")  # float-shaped garbage

    def test_scientific_notation_values(self):
        assert parse_rule("x: t < 2e-2").value == pytest.approx(0.02)
        assert parse_rule("x: t > 1.5E+3 for 10s").value == 1500.0
        assert parse_rule("x: t >= -3e-1").value == pytest.approx(-0.3)

    def test_default_rules_parse(self):
        rules = parse_rules(telemetry.DEFAULT_RULES)
        assert {r.kind for r in rules} == {"absence", "burn_rate",
                                           "threshold"}


# ---------------------------------------------------------------------------
# the alert state machine
# ---------------------------------------------------------------------------

class TestAlertEngine:
    def _engine(self, rules, resolve_s=0.0):
        m = FleetMetrics(MetricsParams(enabled=True))
        rec = flight_recorder.get_recorder("missionctl-test")
        return m, rec, AlertEngine(parse_rules(rules), m,
                                   resolve_s=resolve_s, recorder=rec)

    def test_threshold_pending_dwell_firing_resolved(self):
        m, rec, e = self._engine("hot: t > 10 for 5s")
        t0 = 1000.0
        m.ingest([_row("t", 20.0, t0)])
        tr = e.evaluate(now=t0 + 1)
        assert [x["state"] for x in tr] == ["pending"]
        # dwell not yet served: still pending, no new transition
        assert e.evaluate(now=t0 + 3) == []
        tr = e.evaluate(now=t0 + 7)
        assert [x["state"] for x in tr] == ["firing"]
        assert e.firing() == ["hot"]
        # recovery
        m.ingest([_row("t", 1.0, t0 + 8)])
        tr = e.evaluate(now=t0 + 9)
        assert [x["state"] for x in tr] == ["resolved"]
        snap = {a["rule"]: a for a in e.snapshot()}
        assert snap["hot"]["fired_total"] == 1
        assert snap["hot"]["resolved_total"] == 1
        # resolved relaxes to ok on the next pass
        e.evaluate(now=t0 + 10)
        assert {a["state"] for a in e.snapshot()} == {"ok"}
        kinds = [ev["kind"] for ev in rec.snapshot()]
        assert kinds.count("alert") >= 3  # pending, firing, resolved

    def test_pending_clears_quietly_without_firing(self):
        m, _rec, e = self._engine("hot: t > 10 for 60s")
        t0 = 1000.0
        m.ingest([_row("t", 20.0, t0)])
        e.evaluate(now=t0 + 1)
        m.ingest([_row("t", 1.0, t0 + 2)])
        tr = e.evaluate(now=t0 + 3)
        assert [x["state"] for x in tr] == ["ok"]
        snap = e.snapshot()[0]
        assert snap["fired_total"] == 0 and snap["resolved_total"] == 0

    def test_absence_never_seen_does_not_fire(self):
        """A series that never reported is absent by CONFIGURATION —
        firing on it would page every fleet that runs without the perf
        plane enabled."""
        _m, _rec, e = self._engine("stall: ghost/tag absent 0.1s")
        for dt in (0.0, 10.0, 100.0):
            assert e.evaluate(now=1000.0 + dt) == []
        assert e.snapshot()[0]["state"] == "ok"

    def test_absence_fires_and_resolves(self):
        m, _rec, e = self._engine("stall: t absent 2s")
        t0 = 1000.0
        m.ingest([_row("t", 5.0, t0)])
        assert e.evaluate(now=t0 + 1) == []
        tr = e.evaluate(now=t0 + 3)
        assert [x["state"] for x in tr] == ["pending", "firing"]
        m.ingest([_row("t", 5.0, t0 + 4)])
        tr = e.evaluate(now=t0 + 4.5)
        assert [x["state"] for x in tr] == ["resolved"]

    def test_burn_rate_counts_window_fraction(self):
        m, _rec, e = self._engine("burn: t > 10 frac 0.5 over 60s")
        t0 = 1000.0
        # 3 of 10 samples violating: under budget
        m.ingest([_row("t", 20.0 if i < 3 else 1.0, t0 + i)
                  for i in range(10)])
        assert e.evaluate(now=t0 + 10) == []
        # 8 of 12: over budget -> pending + firing (for_s 0)
        m.ingest([_row("t", 20.0, t0 + 10 + i) for i in range(5)])
        tr = e.evaluate(now=t0 + 15)
        assert [x["state"] for x in tr] == ["pending", "firing"]

    def test_resolve_hysteresis(self):
        m, _rec, e = self._engine("hot: t > 10", resolve_s=5.0)
        t0 = 1000.0
        m.ingest([_row("t", 20.0, t0)])
        e.evaluate(now=t0 + 1)
        m.ingest([_row("t", 1.0, t0 + 2)])
        assert e.evaluate(now=t0 + 3) == []       # clean, inside window
        assert e.snapshot()[0]["state"] == "firing"
        tr = e.evaluate(now=t0 + 9)               # 5 s clean served
        assert [x["state"] for x in tr] == ["resolved"]

    def test_transitions_land_in_scalar_stream(self, tmp_path):
        m = FleetMetrics(MetricsParams(enabled=True))
        writer = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                               role="missionctl")
        e = AlertEngine(parse_rules("hot: t > 10"), m, writer=writer)
        t0 = 1000.0
        m.ingest([_row("t", 20.0, t0)])
        e.evaluate(now=t0 + 1)
        writer.close()
        rows = [r for r in read_scalars(str(tmp_path))
                if r.get("tag", "").startswith("alert/")]
        assert [r["value"] for r in rows] == [1.0, 2.0]  # pending, firing


# ---------------------------------------------------------------------------
# OpenMetrics exposition
# ---------------------------------------------------------------------------

class TestOpenMetrics:
    def test_text_format(self):
        m = FleetMetrics(MetricsParams(enabled=True))
        now = time.time()
        m.ingest([_row("learner/updates_per_s", 123.4, now),
                  _row("actor/env_frames_per_s", 9.0, now,
                       role="actor-1")])
        e = AlertEngine(parse_rules("stall: learner/updates_per_s "
                                    "absent 0.001s"), m)
        e.evaluate(now=now + 10)  # absent -> firing
        text = openmetrics_text(m, e)
        assert "# TYPE tpu_apex_learner_updates_per_s gauge" in text
        assert 'tpu_apex_learner_updates_per_s{role="learner"} 123.4' \
            in text
        assert 'tpu_apex_alert_state{rule="stall",' in text
        assert "tpu_apex_alerts_firing 1" in text
        assert text.rstrip().endswith("# EOF")
        # every non-comment line: name{labels} value [timestamp]
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            assert line.split(" ")[0][0].isalpha()

    def test_label_values_are_escaped(self):
        """A pusher-controlled role/host string with quotes/newlines
        must not make the whole /metrics page unparseable."""
        m = FleetMetrics(MetricsParams(enabled=True))
        m.ingest([_row("t", 1.0, time.time(),
                       role='evil"role\nwith\\stuff')])
        text = openmetrics_text(m)
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("tpu_apex_t{"))
        assert "\n" not in line  # by construction of splitlines
        assert '\\"' in line and "\\n" in line and "\\\\" in line

    def test_http_scrape(self):
        import urllib.request

        m = FleetMetrics(MetricsParams(enabled=True))
        m.ingest([_row("learner/updates_per_s", 7.0, time.time())])
        srv = OpenMetricsServer(lambda: openmetrics_text(m),
                                host="127.0.0.1", port=0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics",
                    timeout=10) as resp:
                assert resp.status == 200
                assert "version=0.0.4" in resp.headers["Content-Type"]
                body = resp.read().decode()
            assert "tpu_apex_learner_updates_per_s" in body
            assert srv.scrapes == 1
            with pytest.raises(Exception):  # noqa: PT011 - 404 surface
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/nope", timeout=10)
        finally:
            srv.close()

    def test_mission_control_serves_openmetrics(self, tmp_path):
        import urllib.request

        mission = MissionControl(
            str(tmp_path),
            MetricsParams(enabled=True, openmetrics=True,
                          openmetrics_port=0),
            AlertParams(rules="stall: t absent 60s"))
        try:
            w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                              role="learner")
            w.scalar("t", 1.5, step=0)
            w.close()
            mission.poll()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{mission.exporter.port}/metrics",
                    timeout=10) as resp:
                body = resp.read().decode()
            assert 'tpu_apex_t{role="learner"} 1.5' in body
            assert "tpu_apex_alert_state" in body
        finally:
            mission.stop()


# ---------------------------------------------------------------------------
# T_METRICS push + clock-offset alignment (ISSUE 10 satellite)
# ---------------------------------------------------------------------------

class _GatewayFixture:
    def __init__(self, mission=None, health=None):
        sink = mission.ingest_remote if mission is not None else None
        self.gw = DcnGateway(
            ParamStore(4), GlobalClock(), ActorStats(),
            put_chunk=lambda items: None, host="127.0.0.1", port=0,
            health=health, metrics_sink=sink)
        self.addr = ("127.0.0.1", self.gw.port)

    def close(self):
        self.gw.close()


class TestTMetricsPush:
    def test_push_round_trip_counts(self):
        mission = MissionControl(None, MetricsParams(enabled=True),
                                 AlertParams(enabled=False))
        fx = _GatewayFixture(mission)
        try:
            reply = push_metrics(fx.addr, [
                _row("t", 1.0, time.time()),
                {"tag": "h", "kind": "histogram", "wall": 0.0},
            ])
            assert reply["accepted"] == 1  # non-scalar rows filtered
            assert isinstance(reply["wall"], float)
            assert mission.metrics.remote_batches == 1
            st = fetch_status(fx.addr)
            assert st["metrics_batches"] == 1
            assert st["metrics_rows"] == 1
        finally:
            fx.close()

    def test_push_without_sink_is_counted_error(self):
        fx = _GatewayFixture(mission=None)
        try:
            reply = push_metrics(fx.addr, [_row("t", 1.0, 0.0)])
            assert reply["accepted"] == 0
            assert "no metrics sink" in reply["error"]
            assert "wall" in reply  # offset estimation still works
        finally:
            fx.close()

    def test_skewed_host_lands_on_gateway_clock(self, tmp_path):
        """The ISSUE-10 satellite: a fleet-host scalar pushed with a
        SKEWED wall clock must land on the gateway's timeline within
        the offset tolerance.  Same 2.5 s skew convention as the
        test_timeline.py offset fixtures: the remote host's clock runs
        2.5 s BEHIND the gateway's."""
        skew = -2.5
        skewed_clock = lambda: time.time() + skew  # noqa: E731
        mission = MissionControl(None, MetricsParams(enabled=True),
                                 AlertParams(enabled=False))
        fx = _GatewayFixture(mission)
        try:
            # the remote host's writer stamps walls with ITS clock
            w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                              role="actor-7")
            w.scalar("actor/env_frames_per_s", 1000.0, step=1,
                     wall=skewed_clock())
            w.close()
            pusher = MetricsPusher(fx.addr, str(tmp_path),
                                   MetricsParams(enabled=True),
                                   clock=skewed_clock)
            n = pusher.push_once()
            assert n == 1
            assert pusher.offset == pytest.approx(-skew, abs=0.5)
            wall, value = mission.metrics.latest(
                "actor/env_frames_per_s")
            assert value == 1000.0
            # aligned onto the gateway clock: ~now, not ~now-2.5
            assert abs(wall - time.time()) < 0.5
        finally:
            fx.close()

    def test_pusher_handshakes_before_first_rows(self, tmp_path):
        """No rows travel before an offset estimate exists — a skewed
        host must never pollute the fleet series with unaligned
        points."""
        mission = MissionControl(None, MetricsParams(enabled=True),
                                 AlertParams(enabled=False))
        fx = _GatewayFixture(mission)
        try:
            w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                              role="actor-0")
            w.scalar("t", 1.0, step=0)
            w.close()
            pusher = MetricsPusher(fx.addr, str(tmp_path),
                                   MetricsParams(enabled=True))
            assert pusher.offset is None
            pusher.push_once()
            assert pusher.offset is not None
            assert mission.metrics.ingested_rows == 1
            assert mission.metrics.remote_batches == 2  # handshake+rows
        finally:
            fx.close()

    def test_push_failure_is_counted_and_rows_retained(self, tmp_path):
        w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                          role="actor-0")
        w.scalar("t", 1.0, step=0)
        w.close()
        pusher = MetricsPusher(("127.0.0.1", _free_port()),
                               str(tmp_path),
                               MetricsParams(enabled=True))
        assert pusher.push_once() == 0
        assert pusher.push_errors == 1
        assert len(pusher._pending) == 1  # retried next cadence

    def test_post_handshake_failure_retains_batch_in_order(self,
                                                           tmp_path):
        """The gateway-restart scenario: the pusher already has an
        offset, pops its batch, and the push RPC dies mid-blip — the
        batch must be RE-PREPENDED (order kept) and delivered whole
        once the gateway is back."""
        mission = MissionControl(None, MetricsParams(enabled=True),
                                 AlertParams(enabled=False))
        fx = _GatewayFixture(mission)
        port = fx.gw.port
        w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                          role="actor-0")
        w.scalar("t", 1.0, step=0)
        w.close()
        pusher = MetricsPusher(("127.0.0.1", port), str(tmp_path),
                               MetricsParams(enabled=True))
        assert pusher.push_once() == 1  # handshake + delivery
        fx.close()  # the blip
        w2 = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                          role="actor-0")
        w2.scalar("t", 2.0, step=1)
        w2.close()
        assert pusher.push_once() == 0
        assert pusher.push_errors == 1
        assert [r["value"] for r in pusher._pending] == [2.0]
        mission2 = MissionControl(None, MetricsParams(enabled=True),
                                  AlertParams(enabled=False))
        gw2 = DcnGateway(ParamStore(4), GlobalClock(), ActorStats(),
                         put_chunk=lambda items: None,
                         host="127.0.0.1", port=port,
                         metrics_sink=mission2.ingest_remote)
        try:
            assert pusher.push_once() == 1  # the retained row lands
            assert mission2.metrics.latest("t")[1] == 2.0
        finally:
            gw2.close()

    def test_pending_backlog_is_capped_and_counted(self, tmp_path):
        w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                          role="actor-0")
        for i in range(30):
            w.scalar("t", float(i), step=i)
        w.close()
        pusher = MetricsPusher(("127.0.0.1", _free_port()),
                               str(tmp_path),
                               MetricsParams(enabled=True))
        pusher.MAX_PENDING = 10
        pusher.push_once()  # dead gateway: rows buffer, oldest shed
        assert len(pusher._pending) == 10
        assert pusher.dropped_rows == 20
        assert [r["value"] for r in pusher._pending][0] == 20.0


class TestScalarsTailBound:
    def test_bounded_poll_catches_up_across_polls(self, tmp_path):
        w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                          role="r")
        for i in range(50):
            w.scalar("t", float(i), step=i)
        w.close()
        tail = ScalarsTail(str(tmp_path), max_bytes=1024)
        rows = []
        for _ in range(100):
            got = tail.poll()
            if not got:
                break
            rows.extend(got)
        assert [r["value"] for r in rows] == [float(i)
                                              for i in range(50)]

    def test_is_scalar_row(self):
        assert is_scalar_row({"tag": "t", "value": 1.0})
        assert not is_scalar_row({"tag": "t", "value": 1.0,
                                  "kind": "histogram"})
        assert not is_scalar_row({"tag": "t", "value": "NaN-string"})
        assert not is_scalar_row({"value": 1.0})


# ---------------------------------------------------------------------------
# fleet_top --json alerts/series blocks (ISSUE 10 satellite; the
# tier-1 smoke alongside test_observability's existing --json smoke)
# ---------------------------------------------------------------------------

class TestFleetTopJson:
    def test_json_gains_alert_and_series_blocks(self):
        mission = MissionControl(
            None, MetricsParams(enabled=True),
            AlertParams(rules="stall: learner/updates_per_s "
                              "absent 0.2s"))
        fx = _GatewayFixture(mission,
                             health=lambda: mission.status_block())
        try:
            push_metrics(fx.addr, [
                _row("learner/updates_per_s", 11.0, time.time())])
            time.sleep(0.3)
            mission.poll()  # absence window served -> firing
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "fleet_top.py"),
                 f"127.0.0.1:{fx.gw.port}", "--json"],
                capture_output=True, text=True, timeout=120,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stderr
            status = json.loads(proc.stdout)
            assert status["alerts"][0]["rule"] == "stall"
            assert status["alerts"][0]["state"] == "firing"
            series = status["series"]["learner/updates_per_s"]
            assert series["latest"] == 11.0
            assert series["points"]
            assert status["telemetry"]["remote_batches"] == 1
        finally:
            fx.close()

    def test_selftest_passes(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "fleet_top.py"),
             "--selftest"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stderr
        assert "PASS" in proc.stderr

    def test_render_shows_alert_panel_and_sparklines(self):
        from tools import fleet_top

        status = {
            "learner_step": 5, "wall": time.time(),
            "alerts": [{"rule": "stall", "tag": "t", "state": "firing",
                        "age": 4.0, "detail": "last sample 9s ago",
                        "fired_total": 1}],
            "series": {"learner/updates_per_s": {
                "points": [[1.0, 1.0], [2.0, 8.0], [3.0, 3.0]],
                "latest": 3.0}},
        }
        panel = fleet_top.render(status)
        assert "alerts: stall FIRING" in panel
        assert "learner/updates_per_s" in panel
        assert any(ch in panel for ch in fleet_top._SPARK)
        ok = dict(status, alerts=[dict(status["alerts"][0], state="ok",
                                       fired_total=2)])
        assert "alerts: ok (1 rule(s), 2 fired lifetime)" \
            in fleet_top.render(ok)


# ---------------------------------------------------------------------------
# config/knob plumbing
# ---------------------------------------------------------------------------

class TestKnobs:
    def test_env_overrides(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_METRICS", "1")
        monkeypatch.setenv("TPU_APEX_METRICS_POLL_S", "0.5")
        monkeypatch.setenv("TPU_APEX_METRICS_OPENMETRICS", "1")
        mp = telemetry.resolve_metrics()
        assert mp.enabled and mp.poll_s == 0.5 and mp.openmetrics
        monkeypatch.setenv("TPU_APEX_ALERT_RULES", "a: t absent 9s")
        monkeypatch.setenv("TPU_APEX_ALERT_RESOLVE_S", "3")
        ap = telemetry.resolve_alerts()
        assert ap.rules == "a: t absent 9s" and ap.resolve_s == 3.0
        assert parse_rules(ap.rules)[0].window_s == 9.0

    def test_options_route_overrides(self):
        from pytorch_distributed_tpu.config import build_options

        opt = build_options(1, poll_s=0.7,
                            rules="a: t absent 1s", resolve_s=2.0)
        assert opt.metrics_params.poll_s == 0.7
        assert opt.alert_params.rules == "a: t absent 1s"
        assert opt.alert_params.resolve_s == 2.0

    def test_ambiguous_override_refused(self):
        """``enabled`` lives on the perf/metrics/alert planes: a bare
        override must refuse loudly instead of flipping all three."""
        from pytorch_distributed_tpu.config import build_options

        with pytest.raises(ValueError, match="ambiguous"):
            build_options(1, enabled=True)


# ---------------------------------------------------------------------------
# bench/gate wiring (ISSUE 10 satellite)
# ---------------------------------------------------------------------------

class TestBenchGateWiring:
    def test_metrics_overhead_key_is_gated(self):
        import bench_gate

        base = {"bench_schema": 4,
                "metrics_overhead": {"metrics_overhead_frac": 0.0}}
        good = {"bench_schema": 4,
                "metrics_overhead": {"metrics_overhead_frac": 0.015}}
        bad = {"bench_schema": 4,
               "metrics_overhead": {"metrics_overhead_frac": 0.03}}
        assert not bench_gate.compare(good, base)["regressions"]
        reg = bench_gate.compare(bad, base)["regressions"]
        assert [r["key"] for r in reg] == \
            ["metrics_overhead.metrics_overhead_frac"]
        assert reg[0]["direction"] == "lower_abs"

    def test_checked_in_baseline_carries_the_section(self):
        with open(os.path.join(_REPO,
                               "BENCH_SMOKE_BASELINE.json")) as f:
            baseline = json.load(f)
        frac = baseline["metrics_overhead"]["metrics_overhead_frac"]
        assert frac is not None and frac < 0.02


# ---------------------------------------------------------------------------
# the acceptance drill: seeded chaos_soak learner stall, end to end
# ---------------------------------------------------------------------------

class TestAcceptanceDrill:
    def test_learner_stall_fires_shows_and_resolves(self, tmp_path):
        """ISSUE 10 acceptance: a seeded ``chaos_soak`` run with an
        injected learner stall raises a ``learner/updates_per_s``
        absence alert that (1) FIRES, (2) is visible in ``fleet_top
        --json`` while firing, (3) appears as transition events on the
        ``tools/timeline.py`` incident timeline, and (4) RESOLVES
        after recovery — through the production components only: the
        soak's simulated learner writes real scalar rows, mission
        control tails them, the gateway serves the alert block over
        the real wire, and the blackbox rings land on disk."""
        import chaos_soak
        import timeline

        port = _free_port()
        box = {}

        def _run():
            box["report"] = chaos_soak.soak(
                seconds=9.0, actors=1, seed=7, restart_every=None,
                poison_every=0, learner_stall=2.5, learner_stall_at=2.0,
                log_dir=str(tmp_path), port=port, verbose=False)

        th = threading.Thread(target=_run, daemon=True)
        th.start()
        # ---- (2) visible in fleet_top --json mid-run, while firing.
        # In-process main(): a subprocess interpreter per poll would
        # outlast the firing window on a slow host.
        from tools import fleet_top

        firing_status = None
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            buf = io.StringIO()
            try:
                with redirect_stdout(buf):
                    rc = fleet_top.main([f"127.0.0.1:{port}", "--json"])
            except SystemExit:  # argparse never exits here; belt+braces
                rc = 1
            if rc == 0:
                status = json.loads(buf.getvalue())
                firing = [a for a in status.get("alerts", [])
                          if a["state"] == "firing"
                          and a["rule"] == "learner_stall"]
                if firing:
                    firing_status = status
                    break
            time.sleep(0.25)
        th.join(30.0)
        assert not th.is_alive(), "soak did not finish"
        report = box["report"]
        assert firing_status is not None, \
            f"alert never visible over fleet_top --json; " \
            f"report={report.get('alerts')}"
        assert "learner/updates_per_s" in firing_status["series"]
        # ---- (1) fired + (4) resolved, and nothing unexpected
        assert report["violations"] == []
        assert report["alerts"]["fired"] == ["learner_stall"]
        assert report["alerts"]["unexpected"] == []
        assert report["alerts"]["unresolved"] == []
        assert report["alerts"]["resolved_total"] >= 1
        # ---- (3) the incident timeline reconstructs the transitions
        events = timeline.build_timeline(str(tmp_path))
        alert_ev = [e for e in events if e["kind"] == "alert"]
        states = [e["data"].get("state") for e in alert_ev]
        assert "firing" in states and "resolved" in states
        assert states.index("firing") < states.index("resolved")
        assert all(e["role"] == "missionctl" for e in alert_ev)
        # the alert/* scalar rows ride the default timeline view too
        assert any(e["kind"] == "scalar"
                   and str(e.get("tag", "")).startswith("alert/")
                   for e in events)

    def test_soak_without_stall_keeps_alert_plane_quiet(self, tmp_path):
        """The negative leg: the same rule set over a HEALTHY simulated
        learner fires nothing — the unexpected-alert invariant the
        chaos gate enforces."""
        import chaos_soak

        report = chaos_soak.soak(
            seconds=4.0, actors=1, seed=3, restart_every=None,
            poison_every=0, learner_stall=0.0,
            alert_rules=chaos_soak.SOAK_ALERT_RULES,
            log_dir=str(tmp_path), verbose=False)
        assert report["violations"] == []
        assert report["alerts"]["fired"] == []
        assert report["alerts"]["stall_injected"] is False


# ---------------------------------------------------------------------------
# topology wiring: the mission rides a real (thread-backend) run
# ---------------------------------------------------------------------------

class TestTopologyWiring:
    @pytest.mark.slow
    @pytest.mark.timeout(300)
    def test_fleet_topology_serves_alert_blocks_live(self, tmp_path,
                                                     monkeypatch):
        """A real FleetTopology with the metrics plane enabled serves
        ``alerts``/``series`` on its gateway STATUS verb while the run
        is still alive, and the aggregator has absorbed the run's own
        scalar stream by the end.  (Slow tier since ISSUE 12's budget
        thinning: ~70s of live-topology wall on this image — the wiring
        itself is smoke-covered by fleet_top --selftest in check.sh and
        the anakin acceptance drill exercises the same STATUS plane.)"""
        from pytorch_distributed_tpu.config import build_options
        from pytorch_distributed_tpu.fleet import FleetTopology

        # another suite's perf-enabled topology may have exported
        # TPU_APEX_PERF via perf.export_env — with it on, this run pays
        # the flops AOT compile + profiler prewarm and the learner's
        # first stats window outlives the probe budget on this host
        for k in list(os.environ):
            if k.startswith("TPU_APEX_PERF"):
                monkeypatch.delenv(k, raising=False)

        opt = build_options(
            1, root_dir=str(tmp_path), refs="telemetry-accept",
            num_actors=1, seed=3,
            # the test ends the run itself (stop event in the finally)
            # once the probe landed; max_seconds is the backstop
            steps=10 ** 9, max_seconds=90.0, learn_start=16,
            memory_size=512, batch_size=16, actor_freq=25,
            learner_freq=50, logger_freq=1, evaluator_nepisodes=0,
            early_stop=50, checkpoint_freq=0)
        opt.metrics_params.enabled = True
        opt.metrics_params.poll_s = 0.2
        opt.alert_params.rules = (
            "stall: learner/critic_loss absent 300s; "
            "quiet: learner/critic_loss > 1e12 for 5s")
        topo = FleetTopology(opt, local_actors=1, port=0)
        assert topo.mission is not None
        done = threading.Event()

        def run():
            try:
                topo.run(backend="thread")
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        seen = {}
        try:
            deadline = time.monotonic() + 75.0
            while time.monotonic() < deadline and not done.is_set():
                try:
                    st = fetch_status(("127.0.0.1", topo.port),
                                      timeout=5.0)
                except (ConnectionError, OSError):
                    st = None
                # wait for the RULE tag specifically: other suites may
                # leave the perf plane's env on, whose tags fill the
                # series block before the logger's first drain lands
                if st and "alerts" in st and "learner/critic_loss" in (
                        st.get("series") or {}):
                    seen.update(st)
                    break
                time.sleep(0.3)
        finally:
            topo.clock.stop.set()
            t.join(120)
        assert not t.is_alive()
        assert "alerts" in seen, "STATUS never carried the alert block"
        assert {a["rule"] for a in seen["alerts"]} == {"stall", "quiet"}
        assert all(a["state"] == "ok" for a in seen["alerts"])
        # a rule tag that reported rides the series block
        assert "learner/critic_loss" in seen["series"]
        # the aggregator tailed the run's own stream
        assert topo.mission.metrics.ingested_rows > 0
        assert "learner/critic_loss" in topo.mission.metrics.tags()
