"""End-to-end topology tests: the full actor/learner/evaluator/logger wiring
on in-process (thread) workers, small configs — the integration layer the
reference only had as "watch TensorBoard" (SURVEY.md §4).
"""

import glob
import os

import numpy as np
import pytest

from pytorch_distributed_tpu import runtime
from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.utils.metrics import read_scalars


def _opts(tmp_path, config, **overrides):
    base = dict(
        root_dir=str(tmp_path),
        num_actors=2,
        steps=300,
        learn_start=64,
        batch_size=32,
        memory_size=2048,
        actor_sync_freq=20,
        param_publish_freq=5,
        learner_freq=50,
        logger_freq=1,
        evaluator_freq=1,
        visualize=False,
        # determinism guards for loaded/parallel CI hosts: the ratio cap
        # keeps a warm-jit learner from burning its step budget before
        # actors fill replay, and a small early_stop guarantees episodes
        # complete (by truncation at worst) while replay is still warming
        # up — stats assertions then never depend on thread scheduling
        max_replay_ratio=16.0,
        early_stop=50,
    )
    base.update(overrides)
    return build_options(config=config, **base)


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_dqn_chain_topology_trains_and_checkpoints(tmp_path):
    # early_stop 25 < learn_start/num_actors: every env slot truncates an
    # episode during replay warmup, before the learner can finish
    opt = _opts(tmp_path, config=1, early_stop=25)
    topo = runtime.train(opt, backend="thread")

    # the global clock ran to completion
    assert topo.clock.learner_step.value >= opt.agent_params.steps
    assert topo.clock.actor_step.value > 0

    # scalars were written with reference tag names
    recs = read_scalars(opt.log_dir)
    tags = {r["tag"] for r in recs}
    assert "learner/critic_loss" in tags
    assert "actor/avg_reward" in tags
    assert "evaluator/avg_reward" in tags

    # evaluator wrote the params-only checkpoint (+ the best-so-far
    # tier); learner the full state
    assert os.path.exists(opt.model_name + ".msgpack")
    assert os.path.exists(opt.model_name + "_best.msgpack")
    assert os.path.isdir(opt.model_name + "_state")

    # mode-2 tester loads the checkpoint and runs greedy episodes
    opt2 = _opts(tmp_path, config=1, mode=2, tester_nepisodes=3,
                 model_file=opt.model_name)
    out = runtime.test(opt2)
    assert out["nepisodes"] == 3.0
    # chain env: any policy terminates (right end or early_stop); sanity only
    assert out["avg_steps"] > 0


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_dqn_chain_learns_optimal_policy(tmp_path):
    # longer run: greedy policy should walk straight down the chain.
    # max_replay_ratio pins the learner/actor pace so the outcome doesn't
    # depend on thread scheduling (a warm jit cache otherwise lets the
    # learner burn its step budget before actors fill the replay).
    # early_stop stays at the env default here (unlike the smoke tests'
    # small caps): on the chain, an uncapped random walk reaches the
    # rewarded end almost surely, so replay always carries reward signal —
    # capping at 50 made roughly half the seeds learn nothing
    opt = _opts(tmp_path, config=1, steps=3000, num_actors=2,
                lr=5e-3, nstep=3, eps=0.5, max_replay_ratio=16.0,
                early_stop=12500)
    runtime.train(opt, backend="thread")
    opt2 = _opts(tmp_path, config=1, mode=2, tester_nepisodes=5,
                 model_file=opt.model_name)
    out = runtime.test(opt2)
    # optimal walk on the 8-chain takes exactly 7 steps and scores 1
    assert out["avg_reward"] >= 0.9
    assert out["avg_steps"] <= 10


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_ddpg_pendulum_topology_runs(tmp_path):
    opt = _opts(tmp_path, config=2, steps=200, learn_start=64,
                batch_size=32)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    recs = read_scalars(opt.log_dir)
    tags = {r["tag"] for r in recs}
    assert "learner/actor_loss" in tags
    assert os.path.exists(opt.model_name + ".msgpack")


@pytest.mark.slow
@pytest.mark.timeout(2400)
def test_ddpg_reacher_learns_reaching(tmp_path):
    # DDPG learning bar (the analogue of test_dqn_chain_learns_optimal_
    # policy for the continuous-control family, reference
    # ddpg_learner.py:50-106): the 2-joint reacher scores ~-30/episode
    # under a random policy and -8..-15 once the arm learns to reach;
    # the mode-2 greedy bar at -20 passes only with real learning.
    # Geometry = the drive-validated recipe (verify notes), shrunk to 4
    # envs per actor for loaded CI hosts.
    opt = _opts(tmp_path, config=16, steps=8000, num_actors=2,
                num_envs_per_actor=4, batch_size=64, memory_size=50000,
                learn_start=1000, max_replay_ratio=8.0,
                evaluator_freq=60, early_stop=12500)
    runtime.train(opt, backend="thread")
    opt2 = _opts(tmp_path, config=16, mode=2, tester_nepisodes=5,
                 model_file=opt.model_name)
    out = runtime.test(opt2)
    assert out["avg_reward"] >= -20.0, (
        f"DDPG failed the reacher learning bar: {out}")


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_per_topology_runs_and_anneals(tmp_path):
    opt = _opts(tmp_path, config=1, memory_type="prioritized", steps=200)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    per = topo.handles.learner_side.memory
    assert per.size > 0
    # priorities were written back: not all slots still at the initial max
    pr = per.sum_tree.get(np.arange(min(per.size, 256)))
    assert len(np.unique(np.round(pr, 6))) > 1


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_resume_from_full_state(tmp_path):
    opt = _opts(tmp_path, config=1, steps=100)
    runtime.train(opt, backend="thread")
    # second run with same refs resumes from the saved TrainState and
    # extends to 150 steps
    opt2 = _opts(tmp_path, config=1, steps=150, refs=opt.refs)
    topo2 = runtime.train(opt2, backend="thread")
    assert topo2.clock.learner_step.value >= 150


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_device_replay_topology_runs(tmp_path):
    # flagship HBM-replay path on the fake env (config 8 is pong-sim; use
    # the same memory_type over the cheap chain env for CI speed)
    opt = _opts(tmp_path, config=1, memory_type="device", steps=200)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    recs = read_scalars(opt.log_dir)
    assert any(r["tag"] == "learner/critic_loss" for r in recs)
    assert topo.handles.learner_side.size > 0


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_native_ring_topology_runs(tmp_path):
    pytest.importorskip("ctypes")
    try:
        from pytorch_distributed_tpu.memory.native_ring import get_lib
        get_lib()
    except Exception:
        pytest.skip("native toolchain unavailable")
    opt = _opts(tmp_path, config=1, memory_type="native", steps=200)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    from pytorch_distributed_tpu.memory.native_ring import NativeRingReplay
    assert isinstance(topo.handles.learner_side, NativeRingReplay)
    assert topo.handles.learner_side.total_feeds > 0


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_ddpg_reacher_multidim_topology_runs(tmp_path):
    """The 2-dim continuous action path end to end: OU noise shaped
    (num_envs, 2), decoupled two-optimizer DDPG update, tester reload."""
    opt = _opts(tmp_path, config=16, steps=200, learn_start=64,
                batch_size=32)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    recs = read_scalars(opt.log_dir)
    tags = {r["tag"] for r in recs}
    assert "learner/actor_loss" in tags and "actor/avg_reward" in tags
    opt2 = _opts(tmp_path, config=16, mode=2, tester_nepisodes=2,
                 model_file=opt.model_name)
    out = runtime.test(opt2)
    assert out["nepisodes"] == 2.0
    assert out["avg_reward"] < 0.0  # negative-cost env; sanity only


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_vector_env_actor_topology(tmp_path):
    # early_stop 12 < learn_start/4 envs: all four env slots truncate an
    # episode during replay warmup regardless of scheduling
    opt = _opts(tmp_path, config=1, steps=300, num_actors=1,
                num_envs_per_actor=4, early_stop=12)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 300
    # 4 envs advance the actor clock 4 per tick
    assert topo.clock.actor_step.value >= 4
    recs = read_scalars(opt.log_dir)
    assert any(r["tag"] == "actor/avg_reward" for r in recs)


@pytest.mark.slow
@pytest.mark.timeout(900)
def test_actor_crash_restarts_elastically(tmp_path):
    """Failure supervision: a dying actor child is respawned in place and
    the run completes (process backend)."""
    import pytorch_distributed_tpu.runtime as rt

    opt = _opts(tmp_path, config=1, steps=150, num_actors=1)
    topo = rt.Topology(opt)

    killed = {"done": False}

    # spawn pickles the child entry by qualified name, so patching it here
    # wouldn't reach the child; simulate the crash by terminating the live
    # actor child once it is up
    import threading, time as _time

    def killer():
        deadline = _time.monotonic() + 120
        while _time.monotonic() < deadline and not killed["done"]:
            for p, role, ind, args in list(getattr(topo, "_proc_meta", [])):
                if role == "actor" and p.is_alive():
                    p.terminate()  # exitcode -SIGTERM != 0 -> restart path
                    killed["done"] = True
                    return
            _time.sleep(0.5)

    t = threading.Thread(target=killer, daemon=True)
    t.start()
    topo.run(backend="process")
    assert killed["done"], "test never saw a live actor to kill"
    assert topo.clock.learner_step.value >= 150
    # the monitor respawned rather than stopping the run
    assert len(topo._proc_meta) >= 3


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_device_per_topology_runs(tmp_path):
    opt = _opts(tmp_path, config=1, memory_type="device-per", steps=200)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 200
    replay = topo.handles.learner_side.replay
    import numpy as np
    pr = np.asarray(replay.state.priority)
    # priorities were written back on device: sampled rows no longer all
    # carry the uniform insert priority
    valid = pr[pr > 0]
    assert len(np.unique(np.round(valid, 6))) > 1
