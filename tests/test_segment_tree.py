import numpy as np
import pytest

from pytorch_distributed_tpu.utils.segment_tree import MinTree, SumTree


def test_sum_tree_total_and_get():
    t = SumTree(10)
    t.set(np.arange(10), np.arange(10, dtype=np.float64))
    assert t.total == pytest.approx(45.0)
    assert t.get(np.array([3, 7])).tolist() == [3.0, 7.0]


def test_sum_tree_find_matches_cumsum():
    rng = np.random.default_rng(0)
    t = SumTree(37)  # non-power-of-two capacity
    pri = rng.uniform(0.0, 5.0, size=37)
    t.set(np.arange(37), pri)
    cum = np.cumsum(pri)
    values = rng.uniform(0.0, t.total, size=1000)
    found = t.find(values)
    expected = np.searchsorted(cum, values, side="right")
    np.testing.assert_array_equal(found, expected)


def test_sum_tree_find_edges():
    t = SumTree(4)
    t.set(np.arange(4), np.array([1.0, 0.0, 2.0, 1.0]))
    assert t.find(np.array([0.0]))[0] == 0
    assert t.find(np.array([0.999]))[0] == 0
    assert t.find(np.array([1.0]))[0] == 2  # zero-priority leaf 1 skipped
    assert t.find(np.array([3.999]))[0] == 3
    # v == total guard never returns out-of-range
    assert t.find(np.array([4.0]))[0] <= 3


def test_sum_tree_update_overwrites():
    t = SumTree(8)
    t.set(np.arange(8), np.ones(8))
    t.set(np.array([2, 2, 5]), np.array([10.0, 3.0, 0.0]))  # duplicate: last wins
    assert t.get(np.array([2]))[0] == 3.0
    assert t.total == pytest.approx(6 * 1.0 + 3.0 + 0.0)


def test_sum_tree_sampling_distribution():
    rng = np.random.default_rng(1)
    t = SumTree(4)
    t.set(np.arange(4), np.array([1.0, 2.0, 3.0, 4.0]))
    counts = np.zeros(4)
    for _ in range(200):
        idx = t.sample(64, rng)
        np.add.at(counts, idx, 1)
    freq = counts / counts.sum()
    np.testing.assert_allclose(freq, np.array([0.1, 0.2, 0.3, 0.4]), atol=0.02)


def test_min_tree():
    t = MinTree(10)
    t.set(np.arange(10), np.arange(1, 11, dtype=np.float64))
    assert t.min == 1.0
    t.set(np.array([9]), np.array([0.25]))
    assert t.min == 0.25


def test_empty_batch_operations_are_noops():
    t = SumTree(10)
    t.set(np.arange(3), np.ones(3))
    t.set(np.array([], dtype=np.int64), np.array([]))
    assert t.total == 3.0
    assert t.find(np.array([])).size == 0
    m = MinTree(10)
    m.set(np.array([], dtype=np.int64), np.array([]))


def test_min_tree_rejects_out_of_range():
    m = MinTree(10)
    with pytest.raises(AssertionError):
        m.set(np.array([12]), np.array([0.01]))
