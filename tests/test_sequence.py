"""R2D2 sequence family: segment assembly, sequence replay, the recurrent
unroll, the n-step-in-window targets, and the end-to-end chain topology."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.memory.sequence_replay import (
    Segment, SegmentBuilder, SequenceReplay,
)


def _carry(v: float, d: int = 4):
    return (np.full(d, v, np.float32), np.full(d, -v, np.float32))


class TestSegmentBuilder:
    def test_overlapping_emission(self):
        b = SegmentBuilder(seq_len=4, overlap=2)
        segs = []
        for t in range(10):
            segs += b.push(np.float32([t]), t % 3, float(t), False,
                           np.float32([t + 1]), _carry(float(t)))
        # windows [0..3], [2..5], [4..7], [6..9]
        assert len(segs) == 4
        s0, s1 = segs[0], segs[1]
        np.testing.assert_array_equal(s0.obs[:, 0], [0, 1, 2, 3, 4])
        np.testing.assert_array_equal(s1.obs[:, 0], [2, 3, 4, 5, 6])
        np.testing.assert_array_equal(s0.action, [0, 1, 2, 0])
        assert s0.mask.sum() == 4
        # stored state is the carry BEFORE the segment's first step
        assert s1.c0[0] == pytest.approx(2.0)
        assert s1.h0[0] == pytest.approx(-2.0)

    def test_episode_end_pads_and_masks(self):
        b = SegmentBuilder(seq_len=5, overlap=2)
        segs = []
        for t in range(3):
            segs += b.push(np.float32([t]), 0, 1.0, t == 2,
                           np.float32([t + 1]), _carry(0.0))
        assert len(segs) == 1
        s = segs[0]
        np.testing.assert_array_equal(s.mask, [1, 1, 1, 0, 0])
        np.testing.assert_array_equal(s.terminal, [0, 0, 1, 0, 0])
        # bootstrap obs sits right after the last valid step; pads repeat it
        assert s.obs[3, 0] == pytest.approx(3.0)
        assert s.obs[5, 0] == pytest.approx(3.0)
        # stream reset: the next episode starts a fresh window
        more = b.push(np.float32([9]), 0, 0.0, False, np.float32([10]),
                      _carry(9.0))
        assert more == [] and len(b._steps) == 1

    def test_no_overlap_across_episodes(self):
        b = SegmentBuilder(seq_len=4, overlap=2)
        for t in range(4):
            b.push(np.float32([t]), 0, 0.0, False, np.float32([t + 1]),
                   _carry(float(t)))
        segs = b.push(np.float32([4]), 0, 1.0, True, np.float32([5]),
                      _carry(4.0))
        # terminal flushes the overlap remainder as its own masked segment
        assert len(segs) == 1
        assert b._steps == []


class TestSequenceReplay:
    def _seg(self, v: float, T=4, d=4):
        return Segment(
            obs=np.full((T + 1, 1), v, np.float32),
            action=np.zeros(T, np.int32),
            reward=np.full(T, v, np.float32),
            terminal=np.zeros(T, np.float32),
            mask=np.ones(T, np.float32),
            c0=np.zeros(d, np.float32), h0=np.zeros(d, np.float32))

    def test_ring_and_uniform_when_alpha_zero(self):
        mem = SequenceReplay(8, 4, (1,), 4, priority_exponent=0.0)
        for i in range(10):  # wraps
            mem.feed(self._seg(float(i)))
        assert mem.size == 8
        batch = mem.sample(16, np.random.default_rng(0))
        assert batch.obs.shape == (16, 5, 1)
        assert (batch.weight == 1.0).all()

    def test_priorities_bias_sampling(self):
        mem = SequenceReplay(8, 4, (1,), 4, priority_exponent=1.0)
        for i in range(8):
            mem.feed(self._seg(float(i)))
        mem.update_priorities(np.arange(8), np.r_[np.zeros(7), 100.0])
        rng = np.random.default_rng(1)
        batch = mem.sample(256, rng)
        # row 7 holds ~all priority mass
        assert (batch.index == 7).mean() > 0.95
        # IS weights: normalized by the max (min-probability row), so the
        # oversampled hot row takes the smallest correction weight
        assert (batch.weight <= 1.0 + 1e-6).all()
        assert batch.weight[batch.index == 7].max() < 1e-3


class TestSequenceLoss:
    def _apply(self):
        # linear "recurrent" net: q = W obs + carry passthrough, so targets
        # are hand-computable; carry = (c, h) each (B, 1)
        def apply(params, obs, carry=None):
            q = obs @ params["w"]  # (B, A)
            if carry is None:
                carry = (jnp.zeros((obs.shape[0], 1)),) * 2
            return q, carry
        return apply

    def test_nstep_window_targets_match_hand_computation(self):
        from pytorch_distributed_tpu.memory.sequence_replay import (
            SegmentBatch,
        )
        from pytorch_distributed_tpu.ops.sequence_losses import (
            build_drqn_train_step,
        )
        from pytorch_distributed_tpu.ops.losses import (
            init_train_state,
        )
        import optax

        T, nstep, gamma = 4, 2, 0.5
        apply = self._apply()
        params = {"w": jnp.eye(1, 3)}  # q(obs)[a] = obs for a=0 else 0
        tx = optax.sgd(0.0)  # zero lr: inspect td via returned priorities
        state = init_train_state(params, tx)
        step = build_drqn_train_step(
            apply, tx, burn_in=0, nstep=nstep, gamma=gamma,
            enable_double=False, target_model_update=10 ** 9,
            rescale_values=False, priority_eta=1.0)

        obs = np.arange(5, dtype=np.float32).reshape(1, 5, 1)  # 0..4
        batch = SegmentBatch(
            obs=obs,
            action=np.zeros((1, T), np.int32),
            reward=np.array([[1.0, 2.0, 3.0, 4.0]], np.float32),
            terminal=np.zeros((1, T), np.float32),
            mask=np.ones((1, T), np.float32),
            c0=np.zeros((1, 1), np.float32),
            h0=np.zeros((1, 1), np.float32),
            weight=np.ones(1, np.float32),
            index=np.zeros(1, np.int32))
        _state, _metrics, seq_pr = jax.jit(step)(state, batch)
        # q_sel[t] = obs[t] = t; boot[s] = max(q(obs[s])) = s
        # t=0: r0 + g r1 + g^2 * boot(2) = 1 + 1 + 0.5 = 2.5, td = 2.5
        # t=1: 2 + 1.5 + 0.25*3 = 4.25, td = 3.25
        # t=2: 3 + 2 + 0.25*4 = 6, td = 4  (boot at 4)
        # t=3 (window end, K=1): 4 + 0.5*boot(4)=6, td=3
        # eta=1 -> max |td| = 4
        assert float(seq_pr[0]) == pytest.approx(4.0, abs=1e-5)

    def test_terminal_cuts_bootstrap(self):
        from pytorch_distributed_tpu.memory.sequence_replay import (
            SegmentBatch,
        )
        from pytorch_distributed_tpu.ops.sequence_losses import (
            build_drqn_train_step,
        )
        from pytorch_distributed_tpu.ops.losses import init_train_state
        import optax

        apply = self._apply()
        params = {"w": jnp.eye(1, 3) * 0.0}  # q == 0 everywhere
        tx = optax.sgd(0.0)
        state = init_train_state(params, tx)
        step = build_drqn_train_step(
            apply, tx, burn_in=0, nstep=3, gamma=0.5,
            enable_double=False, target_model_update=10 ** 9,
            rescale_values=False, priority_eta=1.0)
        # episode ends at t=1 with reward 10; tail padded
        batch = SegmentBatch(
            obs=np.ones((1, 5, 1), np.float32),
            action=np.zeros((1, 4), np.int32),
            reward=np.array([[1.0, 10.0, 0.0, 0.0]], np.float32),
            terminal=np.array([[0.0, 1.0, 0.0, 0.0]], np.float32),
            mask=np.array([[1.0, 1.0, 0.0, 0.0]], np.float32),
            c0=np.zeros((1, 1), np.float32),
            h0=np.zeros((1, 1), np.float32),
            weight=np.ones(1, np.float32),
            index=np.zeros(1, np.int32))
        _state, _m, seq_pr = jax.jit(step)(state, batch)
        # t=0: G = 1 + 0.5*10 = 6 (no bootstrap past terminal), q=0 -> |td|=6
        # t=1: G = 10; |td| = 10 -> max
        assert float(seq_pr[0]) == pytest.approx(10.0, abs=1e-5)


class TestTruncationBootstrap:
    def test_truncated_tail_bootstraps_from_final_obs(self):
        """A time-limit truncation ends the segment WITHOUT a terminal:
        targets near the tail must bootstrap from the stored successor
        observation instead of treating the cut as a death."""
        from pytorch_distributed_tpu.memory.sequence_replay import (
            SegmentBatch,
        )
        from pytorch_distributed_tpu.ops.sequence_losses import (
            build_drqn_train_step,
        )
        from pytorch_distributed_tpu.ops.losses import init_train_state
        import optax

        def apply(params, obs, carry=None):
            q = obs @ params["w"]
            if carry is None:
                carry = (jnp.zeros((obs.shape[0], 1)),) * 2
            return q, carry

        params = {"w": jnp.eye(1, 3)}  # q(obs)[0] = obs
        tx = optax.sgd(0.0)
        state = init_train_state(params, tx)
        step = build_drqn_train_step(
            apply, tx, burn_in=0, nstep=3, gamma=0.5,
            enable_double=False, target_model_update=10 ** 9,
            rescale_values=False, priority_eta=1.0)
        # 2 valid steps (truncated, NO terminal); bootstrap obs = 7 at
        # position 2, repeated through the padding
        obs = np.array([[[1.0], [2.0], [7.0], [7.0], [7.0]]], np.float32)
        batch = SegmentBatch(
            obs=obs,
            action=np.zeros((1, 4), np.int32),
            reward=np.array([[1.0, 1.0, 0.0, 0.0]], np.float32),
            terminal=np.zeros((1, 4), np.float32),
            mask=np.array([[1.0, 1.0, 0.0, 0.0]], np.float32),
            c0=np.zeros((1, 1), np.float32),
            h0=np.zeros((1, 1), np.float32),
            weight=np.ones(1, np.float32),
            index=np.zeros(1, np.int32))
        _state, _m, seq_pr = jax.jit(step)(state, batch)
        # t=0: K=min(3, n_valid-0)=2 -> G = 1 + 0.5*1 + 0.25*boot(7)
        #      = 1.5 + 1.75 = 3.25; q_sel = 1 -> |td| = 2.25
        # t=1: K=1 -> G = 1 + 0.5*7 = 4.5; q_sel = 2 -> |td| = 2.5 (max)
        assert float(seq_pr[0]) == pytest.approx(2.5, abs=1e-5)


class TestRecurrentModel:
    def test_unroll_matches_stepwise(self):
        from pytorch_distributed_tpu.models.drqn import DrqnMlpModel
        from pytorch_distributed_tpu.ops.sequence_losses import unroll

        model = DrqnMlpModel(action_space=3, hidden_dim=16, lstm_dim=8)
        obs = jnp.ones((2, 4))
        params = model.init(jax.random.PRNGKey(0), obs)
        seq = jax.random.normal(jax.random.PRNGKey(1), (5, 2, 4))
        carry = model.zero_carry(2)
        _, q_seq = unroll(model.apply, params, carry, seq)
        c = carry
        for t in range(5):
            q_t, c = model.apply(params, seq[t], c)
            np.testing.assert_allclose(np.asarray(q_seq[t]),
                                       np.asarray(q_t), rtol=1e-5)

    def test_zero_carry_default_matches_explicit(self):
        from pytorch_distributed_tpu.models.drqn import DrqnMlpModel

        model = DrqnMlpModel(action_space=3, lstm_dim=8)
        obs = jnp.ones((2, 4))
        params = model.init(jax.random.PRNGKey(0), obs)
        q_default, _ = model.apply(params, obs)
        q_explicit, _ = model.apply(params, obs, model.zero_carry(2))
        np.testing.assert_allclose(np.asarray(q_default),
                                   np.asarray(q_explicit))


@pytest.mark.slow
@pytest.mark.timeout(1200)
def test_r2d2_chain_topology_learns(tmp_path):
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        13, root_dir=str(tmp_path), num_actors=2, steps=1200, learn_start=8,
        batch_size=16, memory_size=4096, seq_len=16, seq_overlap=8,
        burn_in=4, nstep=3, actor_sync_freq=20, param_publish_freq=5,
        learner_freq=50, evaluator_freq=1, max_replay_ratio=64.0,
        lr=2e-3, target_model_update=100)
    runtime.train(opt, backend="thread")
    opt2 = build_options(13, root_dir=str(tmp_path), mode=2,
                         tester_nepisodes=5, seq_len=16,
                         model_file=opt.model_name)
    out = runtime.test(opt2)
    assert out["avg_reward"] >= 0.9
    assert out["avg_steps"] <= 10


class TestFramePacking:
    """Frame-packed segments (SegmentBuilder pack_frames): the wire/RAM
    representation drops the C-fold stack redundancy; learner-side
    reconstruction must be exact."""

    @staticmethod
    def _stacked_episode(n, C=4, H=6, W=6, seed=0):
        """Simulate a frame-stack env: per-step new frame, stack = last
        C frames (oldest first), reset stack = first frame repeated."""
        rng = np.random.default_rng(seed)
        frames = [rng.integers(0, 255, (H, W)).astype(np.uint8)
                  for _ in range(n + 1)]
        stacks = []
        for t in range(n + 1):
            window = [frames[max(0, t - C + 1 + i)] for i in range(C)]
            stacks.append(np.stack(window))
        return stacks  # obs[t] for t=0..n (obs[n] = bootstrap)

    @pytest.mark.parametrize("overlap", [0, 4])
    def test_packed_reconstruction_matches_stacks(self, overlap):
        # overlap > 0 exercises the retention path: the SECOND emitted
        # segment starts from retained raw steps, and packing must stay
        # exact there too
        import jax

        from pytorch_distributed_tpu.ops.sequence_losses import (
            unpack_frame_stacks,
        )

        T, C = 8, 4
        n_steps = T + (T - overlap)  # enough for two emissions
        stacks = self._stacked_episode(n_steps, C=C)
        packed_b = SegmentBuilder(T, overlap, state_dtype=np.uint8,
                                  pack_frames=C)
        plain_b = SegmentBuilder(T, overlap, state_dtype=np.uint8)
        carry = (np.zeros(3, np.float32), np.zeros(3, np.float32))
        packed, plain = [], []
        for t in range(n_steps):
            args = (stacks[t], t % 3, float(t), t == n_steps - 1,
                    stacks[t + 1], carry)
            packed += packed_b.push(*args)
            plain += plain_b.push(*args)
        assert len(packed) == len(plain) >= 2
        for p, u in zip(packed, plain):
            assert p.obs.shape == (T + C, 6, 6)
            rebuilt = np.asarray(unpack_frame_stacks(
                jax.numpy.asarray(p.obs[None]), C, T))[0]
            np.testing.assert_array_equal(rebuilt, u.obs)

    def test_packed_early_termination_pads_consistently(self):
        import jax

        from pytorch_distributed_tpu.ops.sequence_losses import (
            unpack_frame_stacks,
        )

        T, C, n = 8, 4, 3  # episode dies after 3 steps -> padded tail
        stacks = self._stacked_episode(n, C=C)
        b = SegmentBuilder(T, 0, state_dtype=np.uint8, pack_frames=C)
        carry = (np.zeros(2, np.float32), np.zeros(2, np.float32))
        out = []
        for t in range(n):
            out += b.push(stacks[t], 0, 1.0, t == n - 1, stacks[t + 1],
                          carry)
        seg = out[0]
        assert seg.obs.shape == (T + C, 6, 6)
        rebuilt = np.asarray(unpack_frame_stacks(
            jax.numpy.asarray(seg.obs[None]), C, T))[0]
        # valid positions 0..n-1 and the bootstrap position n are exact
        for t in range(n):
            np.testing.assert_array_equal(rebuilt[t], stacks[t])
        np.testing.assert_array_equal(rebuilt[n], stacks[n])
        # tail is masked: only shape-stability matters there
        assert float(seg.mask[:n].sum()) == n and float(seg.mask[n:].sum()) == 0

    def test_packed_drqn_step_matches_unpacked(self):
        """Same transitions, packed vs stacked wire format -> identical
        loss/priorities from build_drqn_train_step."""
        import jax

        from pytorch_distributed_tpu.memory.sequence_replay import (
            SegmentBatch,
        )
        from pytorch_distributed_tpu.models.drqn import DrqnCnnModel
        from pytorch_distributed_tpu.ops.losses import (
            init_train_state, make_optimizer,
        )
        from pytorch_distributed_tpu.ops.sequence_losses import (
            build_drqn_train_step,
        )

        T, C = 6, 4
        # 36x36: the smallest square that survives the Nature conv
        # stack's VALID 8/4 -> 4/2 -> 3/1 reductions
        stacks = self._stacked_episode(T, C=C, H=36, W=36, seed=3)
        pb = SegmentBuilder(T, 0, state_dtype=np.uint8, pack_frames=C)
        ub = SegmentBuilder(T, 0, state_dtype=np.uint8)
        lstm = 8
        carry = (np.zeros(lstm, np.float32), np.zeros(lstm, np.float32))
        rng = np.random.default_rng(5)
        segs = {}
        for name, b in (("p", pb), ("u", ub)):
            rng2 = np.random.default_rng(5)
            out = []
            for t in range(T):
                out += b.push(stacks[t], int(rng2.integers(3)),
                              float(rng2.normal()), t == T - 1,
                              stacks[t + 1], carry)
            segs[name] = out[0]

        model = DrqnCnnModel(action_space=3, lstm_dim=lstm, norm_val=255.0,
                             compute_dtype=jax.numpy.float32)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, C, 36, 36), np.uint8))
        tx = make_optimizer(lr=1e-3)
        losses = {}
        for name, packed_frames in (("p", C), ("u", 0)):
            s = segs[name]
            batch = SegmentBatch(
                obs=s.obs[None], action=s.action[None],
                reward=s.reward[None], terminal=s.terminal[None],
                mask=s.mask[None], c0=s.c0[None], h0=s.h0[None],
                weight=np.ones(1, np.float32),
                index=np.zeros(1, np.int32))
            step = jax.jit(build_drqn_train_step(
                model.apply, tx, burn_in=2, nstep=3,
                target_model_update=100, packed_frames=packed_frames))
            _st, metrics, pr = step(init_train_state(params, tx), batch)
            losses[name] = (float(metrics["learner/critic_loss"]),
                            float(pr[0]))
        assert losses["p"][0] == pytest.approx(losses["u"][0], rel=1e-5)
        assert losses["p"][1] == pytest.approx(losses["u"][1], rel=1e-5)
