"""RestartBudget — the shared crash-loop policy behind runtime._monitor
and fleet.run_fleet_actors — plus the exit-code vocabulary."""

from __future__ import annotations

import time

from pytorch_distributed_tpu.utils.supervision import (
    EXIT_DISCONNECTED, EXIT_HUNG, EXIT_OK, RestartBudget, describe_exit,
)


def test_budget_exhausts_then_refuses():
    b = RestartBudget(max_restarts=3, grace=300.0)
    b.note_birth(0)
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) is None
    assert b.count(0) == 3


def test_slots_are_independent():
    b = RestartBudget(max_restarts=1)
    b.note_birth(0)
    b.note_birth(1)
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) is None
    assert b.request_restart(1) == 0.0


def test_old_incarnation_resets_budget():
    b = RestartBudget(max_restarts=1, grace=0.0)  # every crash is isolated
    b.note_birth(0)
    for _ in range(5):
        assert b.request_restart(0) is not None


def test_backoff_grows_and_caps():
    b = RestartBudget(max_restarts=10, backoff=True, max_backoff=30.0)
    b.note_birth(0)
    delays = [b.request_restart(0) for _ in range(6)]
    assert delays[:4] == [2.0, 4.0, 8.0, 16.0]
    assert delays[4] == 30.0 and delays[5] == 30.0


def test_backoff_caps_below_two_seconds():
    # max_backoff below the 2 s base must clamp the FIRST delay too
    b = RestartBudget(max_restarts=5, backoff=True, max_backoff=0.5)
    b.note_birth(0)
    assert b.request_restart(0) == 0.5
    assert b.request_restart(0) == 0.5


def test_backoff_resets_after_grace():
    # an incarnation that outlives the grace period proves the previous
    # crash isolated: the budget AND the exponential ladder restart
    b = RestartBudget(max_restarts=4, grace=0.05, backoff=True,
                      max_backoff=30.0)
    b.note_birth(0)
    assert b.request_restart(0) == 2.0
    b.note_birth(0)
    assert b.request_restart(0) == 4.0
    b.note_birth(0)
    time.sleep(0.06)  # this incarnation lived past the grace window
    assert b.request_restart(0) == 2.0  # ladder back at the base
    assert b.count(0) == 1


def test_backoff_does_not_reset_within_grace():
    b = RestartBudget(max_restarts=4, grace=300.0, backoff=True)
    b.note_birth(0)
    assert b.request_restart(0) == 2.0
    b.note_birth(0)  # young incarnation: crash loop continues
    assert b.request_restart(0) == 4.0
    assert b.count(0) == 2


def test_describe_exit_vocabulary():
    assert describe_exit(EXIT_OK) == "exit 0 (run complete)"
    assert "DCN session lost" in describe_exit(EXIT_DISCONNECTED)
    assert describe_exit(EXIT_HUNG) == "exit 4 (hung; watchdog killed)"
    assert describe_exit(-9) == "signal 9"
    assert "crash" in describe_exit(1)


def test_unborn_slot_grants_without_reset():
    # a slot with no recorded birth still gets restarts (a supervisor may
    # observe a crash before its first note_birth) — but from the normal
    # budget, not via the grace-period reset
    b = RestartBudget(max_restarts=1)
    assert b.request_restart(7) == 0.0
    b.note_birth(7)  # callers record the respawn; a young crash then burns
    assert b.request_restart(7) is None


def test_unborn_slot_does_not_refill_budget():
    # regression: _born.get(slot, 0.0) made every unborn slot look like
    # an ancient incarnation, so each crash reset the count to zero and
    # the budget refilled forever — a crash-looping worker whose
    # supervisor never called note_birth was restarted without bound
    b = RestartBudget(max_restarts=2, grace=0.0)  # grace=0: any RECORDED
    # birth would reset; the unborn slot must not
    assert b.request_restart(5) == 0.0
    assert b.request_restart(5) == 0.0
    assert b.request_restart(5) is None
    assert b.count(5) == 2
