"""RestartBudget — the shared crash-loop policy behind runtime._monitor
and fleet.run_fleet_actors."""

from __future__ import annotations

from pytorch_distributed_tpu.utils.supervision import RestartBudget


def test_budget_exhausts_then_refuses():
    b = RestartBudget(max_restarts=3, grace=300.0)
    b.note_birth(0)
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) is None
    assert b.count(0) == 3


def test_slots_are_independent():
    b = RestartBudget(max_restarts=1)
    b.note_birth(0)
    b.note_birth(1)
    assert b.request_restart(0) == 0.0
    assert b.request_restart(0) is None
    assert b.request_restart(1) == 0.0


def test_old_incarnation_resets_budget():
    b = RestartBudget(max_restarts=1, grace=0.0)  # every crash is isolated
    b.note_birth(0)
    for _ in range(5):
        assert b.request_restart(0) is not None


def test_backoff_grows_and_caps():
    b = RestartBudget(max_restarts=10, backoff=True, max_backoff=30.0)
    b.note_birth(0)
    delays = [b.request_restart(0) for _ in range(6)]
    assert delays[:4] == [2.0, 4.0, 8.0, 16.0]
    assert delays[4] == 30.0 and delays[5] == 30.0


def test_unborn_slot_starts_fresh():
    # a slot never marked born reads as an ancient incarnation: the first
    # crash resets its budget then grants (the runtime monitor starts with
    # no recorded births and must still restart a crashed actor)
    b = RestartBudget(max_restarts=1)
    assert b.request_restart(7) == 0.0
    b.note_birth(7)  # callers record the respawn; a young crash then burns
    assert b.request_restart(7) is None
