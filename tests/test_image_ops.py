"""First-party bilinear resize (native/image_ops.cpp + utils/image.py) —
the cv2-free frame preprocessor for the Atari pipeline."""

from __future__ import annotations

import numpy as np
import pytest

from pytorch_distributed_tpu.utils.image import (
    _native_lib, resize_bilinear, resize_bilinear_np,
)


def test_identity_resize():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 255, size=(84, 84)).astype(np.uint8)
    np.testing.assert_array_equal(resize_bilinear_np(img, (84, 84)), img)
    np.testing.assert_array_equal(resize_bilinear(img, (84, 84)), img)


def test_constant_and_ramp():
    const = np.full((210, 160), 77, dtype=np.uint8)
    out = resize_bilinear_np(const, (84, 84))
    assert out.shape == (84, 84)
    np.testing.assert_array_equal(out, 77)
    # a horizontal ramp stays monotone after downscale
    ramp = np.tile(np.linspace(0, 255, 160).astype(np.uint8), (210, 1))
    out = resize_bilinear_np(ramp, (84, 84))
    assert (np.diff(out[0].astype(int)) >= 0).all()


@pytest.mark.skipif(_native_lib() is None,
                    reason="native toolchain unavailable")
@pytest.mark.parametrize("shape,size", [
    ((210, 160), (84, 84)),     # the Atari case (reference atari_env.py:56)
    ((84, 84), (42, 42)),
    ((50, 70), (84, 84)),       # upscale
    ((3, 210, 160), (84, 84)),  # batched frames
])
def test_native_matches_numpy(shape, size):
    rng = np.random.default_rng(1)
    img = rng.integers(0, 256, size=shape).astype(np.uint8)
    np.testing.assert_array_equal(resize_bilinear(img, size),
                                  resize_bilinear_np(img, size))


def test_atari_env_uses_it():
    """AtariEnv imports stay ALE-gated but cv2-free: constructing without
    an ALE wheel raises the ALE ImportError, never a cv2 one."""
    try:
        import ale_py  # noqa: F401
        pytest.skip("ale_py installed; the gate under test is its absence")
    except ImportError:
        pass
    from pytorch_distributed_tpu.config import EnvParams
    from pytorch_distributed_tpu.envs.atari import AtariEnv

    with pytest.raises(ImportError, match="ale_py"):
        AtariEnv(EnvParams(env_type="atari", game="pong"), 0)
