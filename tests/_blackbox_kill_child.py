"""Child process for the flight-recorder SIGKILL drill
(tests/test_observability.py TestFlightRecorder): records a stream of
structured events into a bounded ring while a scripted fault injector
(utils/faults.py) SIGKILLs the process at an exact frame — the injector's
pre-signal ``dump_all`` (the only code that can run before a SIGKILL)
must leave a digestible ``blackbox/<role>.jsonl`` post-mortem behind.
Same pattern as tests/_ckpt_kill_child.py, aimed at the blackbox layer
instead of the checkpoint store.

Run: python _blackbox_kill_child.py <log_dir> <fault_spec>
Prints ``DONE`` only if the schedule never fired (the drill asserts it
does NOT appear).  No jax import — the drill is pure host code.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def main() -> None:
    log_dir, spec = sys.argv[1], sys.argv[2]

    from pytorch_distributed_tpu.utils import flight_recorder
    from pytorch_distributed_tpu.utils.faults import FaultInjector

    flight_recorder.configure(log_dir)
    recorder = flight_recorder.get_recorder("actor-0", capacity=64)
    injector = FaultInjector.scripted(spec, name="blackbox-drill")
    for i in range(10_000):
        recorder.record("tick", i=i)
        injector.frame(b"x")  # fires the schedule (kill@N dumps first)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
