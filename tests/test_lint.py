"""Lint-plane drills (ISSUE 9, tools/apexlint.py).

Mirrors the RetraceDetector drill style of tests/test_perf.py: every
rule gets a FIRE drill (a seeded violation must be caught at the
expected place) and a SILENT drill (the production-shaped idiom the
real code uses must not be flagged) — a rule that cannot pass both is
either blind or noisy.  On top of the per-rule pairs, the dogfood run
lints the real package + tools in tier-1 and must come back with ZERO
unbaselined findings and zero stale baseline entries, without importing
jax (the tool is pure stdlib ``ast``).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools import apexlint

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(apexlint.__file__)))


def lint(tmp_path, sources, rules=None, baseline=None):
    """Write fixture modules under tmp_path and lint them."""
    for rel, src in sources.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return apexlint.run(sorted(sources), root=str(tmp_path),
                        rules=set(rules) if rules else None,
                        baseline=baseline)


def rules_of(report):
    return [f.rule for f in report.findings]


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

class TestDonationAfterUse:
    def test_fires_on_read_after_donating_dispatch(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state):
                step = jax.jit(lambda s: s, donate_argnums=(0,))
                new = step(state)
                return state.sum()
        """}, rules=["donation-after-use"])
        assert rules_of(r) == ["donation-after-use"]
        assert "'state'" in r.findings[0].message
        assert r.findings[0].line == 7  # the read, not the dispatch

    def test_fires_across_loop_iterations(self, tmp_path):
        # the use sits lexically BEFORE the donating call but runs
        # after it on iteration 2 — the classic fused-scan bug shape
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state):
                step = jax.jit(lambda s: s, donate_argnums=(0,))
                for _ in range(4):
                    print(state.shape)
                    out = step(state)
                return out
        """}, rules=["donation-after-use"])
        assert "donation-after-use" in rules_of(r)

    def test_silent_on_exclusive_else_branch(self, tmp_path):
        # the else-branch of the donating call's if can never observe
        # the donation — flow forks at the branch
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state, cond):
                step = jax.jit(lambda s: s, donate_argnums=(0,))
                if cond:
                    new = step(state)
                else:
                    new = state.sum()
                return new
        """}, rules=["donation-after-use"])
        assert r.findings == []

    def test_fires_after_conditional_donation(self, tmp_path):
        # but AFTER the if, either branch may have donated: a read of
        # the buffer on the joined path is still a hazard
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state, cond):
                step = jax.jit(lambda s: s, donate_argnums=(0,))
                if cond:
                    out = step(state)
                return state.sum()
        """}, rules=["donation-after-use"])
        assert "donation-after-use" in rules_of(r)

    def test_silent_on_nested_def_shadowed_local(self, tmp_path):
        # a nested def whose LOCAL happens to share the donated
        # buffer's name is not a read of the buffer
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state):
                step = jax.jit(lambda s: s, donate_argnums=(0,))
                new = step(state)

                def helper():
                    state = [1, 2]
                    return state[0]

                return new, helper
        """}, rules=["donation-after-use"])
        assert r.findings == []

    def test_fires_on_closure_read_of_donated_buffer(self, tmp_path):
        # a genuinely free closure read of the donated buffer IS a
        # hazard (the closure may run after the dispatch)
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state):
                step = jax.jit(lambda s: s, donate_argnums=(0,))
                new = step(state)

                def helper():
                    return state.sum()

                return new, helper
        """}, rules=["donation-after-use"])
        assert "donation-after-use" in rules_of(r)

    def test_silent_on_rebind_idiom(self, tmp_path):
        # the production idiom everywhere in agents/learner + actor:
        # the donated carry is rebound from the dispatch result
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(state, params):
                step = jax.jit(lambda s, p: (s, p), donate_argnums=(0,))
                for _ in range(4):
                    state, aux = step(state, params)
                    print(params)  # params is NOT donated
                return state
        """}, rules=["donation-after-use"])
        assert r.findings == []

    def test_self_attr_jit_registry(self, tmp_path):
        # feed_fn bound on self in __init__, dispatched in a method —
        # the memory/device_replay.py shape
        r = lint(tmp_path, {"m.py": """
            import jax

            class Ring:
                def __init__(self):
                    self._feed = jax.jit(lambda s, c: s, donate_argnums=0)

                def bad(self, state, chunk):
                    out = self._feed(state, chunk)
                    return state.fill

                def good(self, state, chunk):
                    state = self._feed(state, chunk)
                    return state.fill
        """}, rules=["donation-after-use"])
        assert rules_of(r) == ["donation-after-use"]
        assert r.findings[0].context.endswith("bad")


# ---------------------------------------------------------------------------
# rng-key-reuse
# ---------------------------------------------------------------------------

class TestRngKeyReuse:
    def test_fires_on_double_consumption(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(key):
                a = jax.random.uniform(key, (3,))
                b = jax.random.normal(key, (3,))
                return a + b
        """}, rules=["rng-key-reuse"])
        assert rules_of(r) == ["rng-key-reuse"]
        assert "consumed" in r.findings[0].message

    def test_fires_on_use_after_split(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(key):
                k1, k2 = jax.random.split(key)
                return jax.random.uniform(key, (3,))
        """}, rules=["rng-key-reuse"])
        assert rules_of(r) == ["rng-key-reuse"]

    def test_silent_on_split_per_consumer_and_fold_contract(self, tmp_path):
        # the tick_keys contract: the base key is re-folded forever and
        # never consumed directly; split outputs feed one draw each
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(base_key):
                k1, k2 = jax.random.split(base_key)
                a = jax.random.uniform(k1, (3,))
                b = jax.random.normal(k2, (3,))
                for t in range(4):
                    kt = jax.random.fold_in(base_key, t)
                    a = a + jax.random.uniform(kt, (3,))
                return a + b
        """}, rules=["rng-key-reuse"])
        assert r.findings == []

    def test_silent_on_loop_rebind(self, tmp_path):
        # agents/learner.py:~591 — split amortized over a buffer, the
        # operand rebound from the split's own output
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(device_key):
                buf = []
                while True:
                    keys = jax.random.split(device_key, 65)
                    device_key = keys[0]
                    buf = list(keys[1:])
        """}, rules=["rng-key-reuse"])
        assert r.findings == []

    def test_literal_seed_fires_outside_rngs(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f():
                return jax.random.PRNGKey(42)
        """}, rules=["rng-key-reuse"])
        assert rules_of(r) == ["rng-key-reuse"]
        assert "literal seed" in r.findings[0].message

    def test_silent_on_exclusive_branch_consumers(self, tmp_path):
        # only one branch ever executes: consuming the same key in
        # mutually exclusive if/else arms is not reuse
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(key, flag):
                if flag:
                    x = jax.random.uniform(key, (3,))
                else:
                    x = jax.random.normal(key, (3,))
                return x
        """}, rules=["rng-key-reuse"])
        assert r.findings == []

    def test_fires_on_consumption_after_branch_consumption(self, tmp_path):
        # but after the join, a branch may have consumed the key — a
        # further draw is reuse on that path
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(key, flag):
                if flag:
                    x = jax.random.uniform(key, (3,))
                return jax.random.normal(key, (3,))
        """}, rules=["rng-key-reuse"])
        assert "rng-key-reuse" in rules_of(r)

    def test_literal_seed_silent_in_rngs_and_for_derived(self, tmp_path):
        r = lint(tmp_path, {
            "utils/rngs.py": """
                import jax

                def root(root_seed):
                    return jax.random.PRNGKey(0)
            """,
            "m.py": """
                import jax

                def f(seed):
                    return jax.random.PRNGKey(seed)
            """}, rules=["rng-key-reuse"])
        assert r.findings == []


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

class TestRetraceHazard:
    def test_fires_on_loop_counter_into_jit(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(params):
                step = jax.jit(lambda p, t: p)
                for i in range(100):
                    step(params, i)
        """}, rules=["retrace-hazard"])
        assert rules_of(r) == ["retrace-hazard"]
        assert "'i'" in r.findings[0].message

    def test_fires_on_bumped_host_counter(self, tmp_path):
        # the weak-typed tick leak the runtime RetraceDetector drill
        # seeds (tests/test_perf.py): a python int bumped per dispatch
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(params, clock):
                step = jax.jit(lambda p, t: p)
                tick = 0
                while clock.running():
                    step(params, tick)
                    tick += 8
        """}, rules=["retrace-hazard"])
        assert rules_of(r) == ["retrace-hazard"]

    def test_silent_on_device_resident_tick(self, tmp_path):
        # agents/actor.py device loop idiom: tick0 = jnp.int32(0),
        # advanced arithmetically — stays a traced array, never retraces
        r = lint(tmp_path, {"m.py": """
            import jax
            import jax.numpy as jnp

            def f(params, clock):
                step = jax.jit(lambda p, t: p)
                tick0 = jnp.int32(0)
                while clock.running():
                    out = step(params, tick0)
                    tick0 = tick0 + 8
                return out
        """}, rules=["retrace-hazard"])
        assert r.findings == []

    def test_fires_on_unhashable_static_arg(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(x):
                g = jax.jit(lambda a, shape: a, static_argnums=(1,))
                for _ in range(2):
                    g(x, [84, 84])
        """}, rules=["retrace-hazard"])
        assert rules_of(r) == ["retrace-hazard"]
        assert "unhashable" in r.findings[0].message

    def test_silent_on_hashable_static_arg(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import jax

            def f(x):
                g = jax.jit(lambda a, shape: a, static_argnums=(1,))
                for _ in range(2):
                    g(x, (84, 84))
        """}, rules=["retrace-hazard"])
        assert r.findings == []


# ---------------------------------------------------------------------------
# single-owner
# ---------------------------------------------------------------------------

_OWNER_SRC = """
    class RingOwner:
        __apex_mutators__ = ("drain",)
        __apex_owner__ = ("agents.learner",)

        def drain(self):
            return 0

        def pump(self):
            return self.drain()  # defining module: always allowed
"""


class TestSingleOwner:
    def test_fires_outside_owner_set(self, tmp_path):
        r = lint(tmp_path, {
            "pkg/owner.py": _OWNER_SRC,
            "pkg/rogue.py": """
                from pkg.owner import RingOwner

                def f():
                    o = RingOwner()
                    return o.drain()
            """}, rules=["single-owner"])
        assert rules_of(r) == ["single-owner"]
        assert r.findings[0].path == "pkg/rogue.py"

    def test_silent_in_owner_module_and_defining_module(self, tmp_path):
        r = lint(tmp_path, {
            "pkg/owner.py": _OWNER_SRC,
            "pkg/agents/learner.py": """
                from pkg.owner import RingOwner

                def f():
                    o = RingOwner()
                    return o.drain()
            """}, rules=["single-owner"])
        assert r.findings == []

    def test_factory_receiver_resolution(self, tmp_path):
        # health.get_quarantine(...).put(...) — chained factory call
        r = lint(tmp_path, {
            "pkg/health.py": """
                __apex_factories__ = {"get_store": "Store"}

                class Store:
                    __apex_mutators__ = ("put",)
                    __apex_owner__ = ("memory.",)

                    def put(self, items):
                        pass

                def get_store(name):
                    return Store()
            """,
            "pkg/stray.py": """
                from pkg.health import get_store

                def f(items):
                    get_store("x").put(items)
            """,
            "pkg/memory/feeder.py": """
                from pkg.health import get_store

                def f(items):
                    get_store("x").put(items)
            """}, rules=["single-owner"])
        assert rules_of(r) == ["single-owner"]
        assert r.findings[0].path == "pkg/stray.py"

    def test_module_fn_owners(self, tmp_path):
        r = lint(tmp_path, {
            "pkg/ring.py": """
                __apex_fn_owners__ = {"ring_write": ("memory.",)}

                def ring_write(state):
                    return state
            """,
            "pkg/stray.py": """
                from pkg.ring import ring_write

                def f(state):
                    return ring_write(state)
            """,
            "pkg/memory/per.py": """
                from pkg.ring import ring_write

                def f(state):
                    return ring_write(state)
            """}, rules=["single-owner"])
        assert rules_of(r) == ["single-owner"]
        assert r.findings[0].path == "pkg/stray.py"

    def test_real_annotations_are_discovered(self):
        """The production classes declare the ownership registry the
        rule is driven by (QueueOwner/ingests/quarantine + ring fns)."""
        from pytorch_distributed_tpu.memory.feeder import QueueOwner
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplayIngest,
        )
        from pytorch_distributed_tpu.utils.health import QuarantineStore

        assert "drain" in QueueOwner.__apex_mutators__
        assert any("learner" in o for o in QueueOwner.__apex_owner__)
        assert "drain" in DeviceReplayIngest.__apex_mutators__
        assert "put" in QuarantineStore.__apex_mutators__


# ---------------------------------------------------------------------------
# schema-contract
# ---------------------------------------------------------------------------

class TestSchemaContract:
    def test_fires_on_positional_index(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            def f(rows):
                t = Transition(1, 2, 3, 4, 5, 6)
                return t[0]
        """}, rules=["schema-contract"])
        assert rules_of(r) == ["schema-contract"]
        assert ".state0" in r.findings[0].hint

    def test_silent_on_named_fields(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            def f(rows):
                t = Transition(1, 2, 3, 4, 5, 6)
                return t.state0, t.gamma_n
        """}, rules=["schema-contract"])
        assert r.findings == []

    def test_fires_on_shadow_schema_tuple(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            FIELDS = ("state0", "action", "reward", "gamma_n")
        """}, rules=["schema-contract"])
        assert rules_of(r) == ["schema-contract"]
        assert "re-typed" in r.findings[0].message

    def test_silent_on_short_field_subsets(self, tmp_path):
        # utils/health.py-style scalar-column lists are fine
        r = lint(tmp_path, {"m.py": """
            SCALARS = ("reward", "gamma_n", "terminal1")
        """}, rules=["schema-contract"])
        assert r.findings == []

    def test_fires_on_transition_fields_attr(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            def f():
                return list(Transition._fields)
        """}, rules=["schema-contract"])
        assert rules_of(r) == ["schema-contract"]
        assert "REPLAY_FIELDS" in r.findings[0].hint

    def test_wire_columns_drift_fires(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            from schema import REPLAY_FIELDS

            WIRE_COLUMNS = REPLAY_FIELDS + ("priority",)

            def encode_chunk(items):
                cols = {}
                cols["priority"] = 1.0
                cols["bogus"] = 2.0
                return cols
        """}, rules=["schema-contract"])
        assert rules_of(r) == ["schema-contract"]
        assert "'bogus'" in r.findings[0].message

    def test_wire_columns_declared_stays_silent(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            from schema import REPLAY_FIELDS

            WIRE_COLUMNS = REPLAY_FIELDS + ("priority", "trace_id")

            def decode_chunk(cols):
                return cols["state0"], cols.get("trace_id")
        """}, rules=["schema-contract"])
        assert r.findings == []


# ---------------------------------------------------------------------------
# knob-registry
# ---------------------------------------------------------------------------

_KNOB_DOCS = {
    "README.md": "knobs: TPU_APEX_DEMO and TPU_APEX_FAM_ families\n",
    "TESTING.md": "drill knobs: TPU_APEX_DEMO, TPU_APEX_FAM_*\n",
}


def _write_docs(tmp_path, docs=_KNOB_DOCS):
    for name, text in docs.items():
        (tmp_path / name).write_text(text)


class TestKnobRegistry:
    def test_undeclared_read_fires(self, tmp_path):
        _write_docs(tmp_path)
        r = lint(tmp_path, {
            "config.py": 'KNOBS = (("TPU_APEX_DEMO", "m.py", "demo"),)\n',
            "m.py": """
                import os

                def f():
                    return os.environ.get("TPU_APEX_BOGUS")
            """}, rules=["knob-registry"])
        assert any("TPU_APEX_BOGUS" in f.message for f in r.findings)

    def test_declared_documented_read_is_silent(self, tmp_path):
        _write_docs(tmp_path)
        r = lint(tmp_path, {
            "config.py": 'KNOBS = (("TPU_APEX_DEMO", "m.py", "demo"),)\n',
            "m.py": """
                import os

                def f():
                    return os.environ.get("TPU_APEX_DEMO")
            """}, rules=["knob-registry"])
        assert r.findings == []

    def test_family_prefix_constant_resolves(self, tmp_path):
        # the utils/health.py resolve() idiom: _ENV_PREFIX + field
        _write_docs(tmp_path)
        r = lint(tmp_path, {
            "config.py":
                'KNOBS = (("TPU_APEX_FAM_*", "m.py", "family"),)\n',
            "m.py": """
                import os

                _ENV_PREFIX = "TPU_APEX_FAM_"

                def resolve(field):
                    return os.environ.get(_ENV_PREFIX + field.upper())
            """}, rules=["knob-registry"])
        assert r.findings == []

    def test_declared_but_never_read_fires(self, tmp_path):
        _write_docs(tmp_path, {
            "README.md": "TPU_APEX_DEMO TPU_APEX_DEAD\n",
            "TESTING.md": "TPU_APEX_DEMO TPU_APEX_DEAD\n"})
        r = lint(tmp_path, {
            "config.py": ('KNOBS = (("TPU_APEX_DEMO", "m.py", "demo"),\n'
                          '         ("TPU_APEX_DEAD", "m.py", "dead"),)\n'),
            "m.py": """
                import os

                def f():
                    return os.environ.get("TPU_APEX_DEMO")
            """}, rules=["knob-registry"])
        assert any("never read" in f.message for f in r.findings)

    def test_undocumented_knob_fires_per_doc(self, tmp_path):
        _write_docs(tmp_path, {"README.md": "TPU_APEX_DEMO\n",
                               "TESTING.md": "nothing here\n"})
        r = lint(tmp_path, {
            "config.py": 'KNOBS = (("TPU_APEX_DEMO", "m.py", "demo"),)\n',
            "m.py": """
                import os

                def f():
                    return os.environ.get("TPU_APEX_DEMO")
            """}, rules=["knob-registry"])
        assert any("TESTING.md" in f.message for f in r.findings)
        assert not any("README.md" in f.message for f in r.findings)

    def test_param_propagation_through_env_helper(self, tmp_path):
        # utils/tracing.py shape: the read happens inside _env_flag and
        # the knob name arrives from its call sites
        _write_docs(tmp_path)
        r = lint(tmp_path, {
            "config.py": 'KNOBS = (("TPU_APEX_DEMO", "m.py", "demo"),)\n',
            "m.py": """
                import os

                def _env_flag(name, default):
                    raw = os.environ.get(name)
                    return default if raw is None else raw == "1"

                def active():
                    return _env_flag("TPU_APEX_DEMO", True)
            """}, rules=["knob-registry"])
        assert r.findings == []


# ---------------------------------------------------------------------------
# generic pass
# ---------------------------------------------------------------------------

class TestGenericPass:
    def test_unused_import_fires(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            import os
            import sys

            def f():
                return sys.platform
        """}, rules=["unused-import"])
        assert rules_of(r) == ["unused-import"]
        assert "'os'" in r.findings[0].message

    def test_unused_import_exemptions(self, tmp_path):
        # __init__ re-export surface, explicit as-reexport, __all__
        r = lint(tmp_path, {
            "pkg/__init__.py": "import os\n",
            "m.py": """
                import os as os
                import sys

                __all__ = ("sys",)
            """}, rules=["unused-import"])
        assert r.findings == []

    def test_undefined_name_fires(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            def f():
                return bogus_name + 1
        """}, rules=["undefined-name"])
        assert rules_of(r) == ["undefined-name"]

    def test_undefined_silent_on_nested_comprehension_scopes(self, tmp_path):
        # the memory/device_replay.py idiom that defeats naive scopers:
        # a comprehension inside a genexp inside a call, plus lambdas
        r = lint(tmp_path, {"m.py": """
            def f(rows, fields, g):
                out = g(*(
                    [g(r, f) for r in rows]
                    for f in fields))
                h = sorted(fields, key=lambda p: -sum(
                    len(p) for _ in rows))
                return out, h
        """}, rules=["undefined-name"])
        assert r.findings == []

    def test_shadowed_builtin_fires_and_pragma_silences(self, tmp_path):
        r = lint(tmp_path, {"m.py": """
            def f(list):
                dict = 1  # apexlint: ignore[shadowed-builtin]
                return list, dict
        """}, rules=["shadowed-builtin"])
        assert rules_of(r) == ["shadowed-builtin"]
        assert r.findings[0].message.startswith("'list'")

    def test_parse_error_is_a_finding(self, tmp_path):
        r = lint(tmp_path, {"m.py": "def f(:\n"})
        assert rules_of(r) == ["parse-error"]

    def test_null_byte_source_is_a_finding_not_a_crash(self, tmp_path):
        (tmp_path / "nul.py").write_bytes(b"X = 1\x00\n")
        r = apexlint.run(["nul.py"], root=str(tmp_path))
        assert rules_of(r) == ["parse-error"]


# ---------------------------------------------------------------------------
# baseline workflow + CLI
# ---------------------------------------------------------------------------

class TestBaselineAndCli:
    def _finding_fixture(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import jax\n\n\ndef f():\n"
            "    return jax.random.PRNGKey(7)\n")

    def test_baseline_suppresses_and_detects_stale(self, tmp_path):
        self._finding_fixture(tmp_path)
        rep = apexlint.run(["m.py"], root=str(tmp_path),
                           rules={"rng-key-reuse"})
        assert len(rep.findings) == 1
        f = rep.findings[0]
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"entries": [
            {"rule": f.rule, "path": f.path, "context": f.context,
             "message": f.message, "justification": "drill fixture"},
            # in-scope (same scanned file + rule) but matching nothing:
            # must surface as stale so the baseline gets pruned
            {"rule": "rng-key-reuse", "path": f.path, "context": "gone",
             "message": "no longer exists",
             "justification": "stale on purpose"},
        ]}))
        rep2 = apexlint.run(["m.py"], root=str(tmp_path),
                            rules={"rng-key-reuse"},
                            baseline=str(base))
        assert rep2.findings == [] and rep2.suppressed == 1
        assert len(rep2.stale) == 1 and not rep2.clean

    def test_empty_justification_is_an_error(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"entries": [
            {"rule": "x", "path": "m.py", "context": "", "message": "m",
             "justification": "  "}]}))
        with pytest.raises(apexlint.BaselineError):
            apexlint.load_baseline(str(base))
        base.write_text(json.dumps({"entries": [
            {"rule": "x", "path": "m.py", "context": "", "message": "m",
             "justification": "TODO: justify or fix"}]}))
        with pytest.raises(apexlint.BaselineError):
            apexlint.load_baseline(str(base))

    def test_cli_exit_codes_and_json(self, tmp_path):
        self._finding_fixture(tmp_path)
        rc = apexlint.main(["m.py", "--root", str(tmp_path),
                            "--rules", "rng-key-reuse", "--json"])
        assert rc == 1
        (tmp_path / "clean.py").write_text("X = 1\n")
        rc = apexlint.main(["clean.py", "--root", str(tmp_path),
                            "--json"])
        assert rc == 0
        assert apexlint.main(["--rules", "not-a-rule"]) == 2

    def test_subset_runs_carry_out_of_scope_entries(self, tmp_path):
        """A --rules/--paths subset invocation must neither fail on
        baseline entries it could never match nor destroy them."""
        self._finding_fixture(tmp_path)
        (tmp_path / "clean.py").write_text("X = 1\n")
        base = tmp_path / "base.json"
        rep = apexlint.run(["m.py"], root=str(tmp_path),
                           rules={"rng-key-reuse"})
        f = rep.findings[0]
        base.write_text(json.dumps({"entries": [
            {"rule": f.rule, "path": f.path, "context": f.context,
             "message": f.message, "justification": "drill fixture"}]}))
        # rule subset that excludes rng-key-reuse: entry is carried,
        # not stale — the run stays clean
        rep2 = apexlint.run(["m.py"], root=str(tmp_path),
                            rules={"unused-import"},
                            baseline=str(base))
        assert rep2.clean and rep2.stale == []
        assert len(rep2.carried_entries) == 1
        # path subset that excludes m.py: same carry semantics
        rep3 = apexlint.run(["clean.py"], root=str(tmp_path),
                            baseline=str(base))
        assert rep3.clean and len(rep3.carried_entries) == 1

    def test_one_entry_suppresses_exactly_one_finding(self, tmp_path):
        """Two identical violations + one justified entry: the second
        must surface as a finding, not ride the first's
        justification."""
        (tmp_path / "m.py").write_text(
            "import jax\n\n\ndef f():\n"
            "    a = jax.random.PRNGKey(7)\n"
            "    b = jax.random.PRNGKey(7)\n"
            "    return a, b\n")
        rep = apexlint.run(["m.py"], root=str(tmp_path),
                           rules={"rng-key-reuse"})
        assert len(rep.findings) == 2
        f = rep.findings[0]
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"entries": [
            {"rule": f.rule, "path": f.path, "context": f.context,
             "message": f.message, "justification": "only one"}]}))
        rep2 = apexlint.run(["m.py"], root=str(tmp_path),
                            rules={"rng-key-reuse"},
                            baseline=str(base))
        assert rep2.suppressed == 1 and len(rep2.findings) == 1

    def test_deleted_file_entries_go_stale_on_dir_runs(self, tmp_path):
        """An entry for a file deleted from a scanned directory must be
        reported stale (the baseline shrinks), not carried forever."""
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "live.py").write_text("X = 1\n")
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"entries": [
            {"rule": "unused-import", "path": "pkg/gone.py",
             "context": "", "message": "'os' is imported but never "
             "used", "justification": "file was deleted"}]}))
        rep = apexlint.run(["pkg"], root=str(tmp_path),
                           baseline=str(base))
        assert len(rep.stale) == 1 and not rep.clean
        assert rep.carried_entries == []

    def test_write_baseline_preserves_justified_entries(self, tmp_path):
        """Regenerating the baseline must keep matched entries'
        written justifications and only skeleton NEW findings."""
        self._finding_fixture(tmp_path)
        (tmp_path / "n.py").write_text(
            "import jax\n\n\ndef g():\n"
            "    return jax.random.PRNGKey(9)\n")
        base = tmp_path / "base.json"
        rep = apexlint.run(["m.py"], root=str(tmp_path),
                           rules={"rng-key-reuse"})
        f = rep.findings[0]
        base.write_text(json.dumps({"entries": [
            {"rule": f.rule, "path": f.path, "context": f.context,
             "message": f.message, "justification": "keep me"}]}))
        out = tmp_path / "regen.json"
        rc = apexlint.main(["m.py", "n.py", "--root", str(tmp_path),
                            "--rules", "rng-key-reuse",
                            "--baseline", str(base),
                            "--write-baseline", str(out)])
        assert rc == 1  # the n.py finding is new
        entries = json.loads(out.read_text())["entries"]
        justs = {e["path"]: e["justification"] for e in entries}
        assert justs["m.py"] == "keep me"
        assert "TODO" in justs["n.py"]

    def test_wildcard_read_does_not_mask_dead_knob_check(self, tmp_path):
        """An opaque dynamic env read ('*' pattern) must not cover
        declared-but-never-read knobs."""
        (tmp_path / "README.md").write_text("TPU_APEX_DEAD\n")
        (tmp_path / "TESTING.md").write_text("TPU_APEX_DEAD\n")
        r = lint(tmp_path, {
            "config.py":
                'KNOBS = (("TPU_APEX_DEAD", "m.py", "dead"),)\n',
            "m.py": """
                import os

                def f(role):
                    return os.environ.get(role.upper())
            """}, rules=["knob-registry"])
        assert any("never read" in f.message for f in r.findings)

    def test_write_baseline_skeleton_requires_justification(self, tmp_path):
        self._finding_fixture(tmp_path)
        out = tmp_path / "skel.json"
        rc = apexlint.main(["m.py", "--root", str(tmp_path),
                            "--rules", "rng-key-reuse",
                            "--write-baseline", str(out)])
        assert rc == 1  # findings existed
        with pytest.raises(apexlint.BaselineError):
            apexlint.load_baseline(str(out))  # TODO justifications

    def test_cli_subprocess_json_smoke(self, tmp_path):
        (tmp_path / "m.py").write_text("import os\n")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "apexlint.py"),
             "m.py", "--root", str(tmp_path), "--json"],
            capture_output=True, text=True)
        assert proc.returncode == 1
        payload = json.loads(proc.stdout)
        assert payload["counts"] == {"unused-import": 1}


# ---------------------------------------------------------------------------
# the dogfood run: the real package must lint clean in tier-1
# ---------------------------------------------------------------------------

class TestDogfood:
    def test_package_and_tools_lint_clean(self):
        """ISSUE 9 acceptance: zero unbaselined findings, zero stale
        baseline entries, across ALL rules including the generic
        pass."""
        baseline = os.path.join(REPO_ROOT, "tools",
                                "apexlint_baseline.json")
        rep = apexlint.run(["pytorch_distributed_tpu", "tools"],
                           root=REPO_ROOT, baseline=baseline)
        msgs = [f.format() for f in rep.findings]
        assert rep.findings == [], "\n".join(msgs)
        assert rep.stale == [], rep.stale
        assert rep.files > 80  # the whole package actually scanned

    def test_no_jax_import(self):
        """The linter must stay usable on jax-less CI hosts (and fast:
        importing jax costs seconds on the 2-vCPU image)."""
        script = (
            "import sys, importlib.util\n"
            "class Blocker:\n"
            "    def find_module(self, name, path=None):\n"
            "        if name.split('.')[0] == 'jax':\n"
            "            raise ImportError('jax import blocked')\n"
            "sys.meta_path.insert(0, Blocker())\n"
            "spec = importlib.util.spec_from_file_location('apexlint', "
            f"{os.path.join(REPO_ROOT, 'tools', 'apexlint.py')!r})\n"
            "m = importlib.util.module_from_spec(spec)\n"
            "sys.modules['apexlint'] = m\n"
            "spec.loader.exec_module(m)\n"
            "assert m.main(['--list-rules']) == 0\n"
            "print('OK')\n")
        proc = subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True)
        assert proc.returncode == 0 and "OK" in proc.stdout, proc.stderr

    def test_knob_registry_matches_reality(self):
        """config.KNOBS covers the knobs the repo actually documents as
        its surface (a canary beyond the mechanical rule)."""
        from pytorch_distributed_tpu.config import KNOBS

        names = {k[0] for k in KNOBS}
        for expected in ("TPU_APEX_PERF", "TPU_APEX_PERF_*",
                         "TPU_APEX_HEALTH_*", "TPU_APEX_QUARANTINE",
                         "*_FAULTS", "DCN_FAULTS_*"):
            assert expected in names
        # every row is (name, where, doc) with substance
        for name, where, doc in KNOBS:
            assert name and where.endswith(".py") and len(doc) > 8

    def test_check_sh_lint_stage(self):
        """The pre-PR gate's lint stage passes on the repo as checked
        in (bench stages skipped: they have their own tier + budget)."""
        proc = subprocess.run(
            ["bash", os.path.join(REPO_ROOT, "tools", "check.sh")],
            env={**os.environ, "APEXLINT_ONLY": "1"},
            cwd=REPO_ROOT, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "apexlint: PASS" in proc.stdout
