"""Native C++ lock-free ring buffer tests, including the cross-process
hammer the reference's locked design never needed (SURVEY.md §5 "race
detection: none")."""

import multiprocessing as mp

import numpy as np
import pytest

pytest.importorskip("ctypes")

try:
    from pytorch_distributed_tpu.memory.native_ring import (
        NativeRingReplay, get_lib,
    )

    get_lib()
    HAVE_NATIVE = True
except Exception:  # noqa: BLE001 - no toolchain in this image
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE,
                                reason="native toolchain unavailable")

from pytorch_distributed_tpu.utils.experience import Transition  # noqa: E402


def _tr(i, state_shape=(4,)):
    return Transition(
        state0=np.full(state_shape, i % 250, np.float32),
        action=np.int32(i % 4),
        reward=np.float32(i),
        gamma_n=np.float32(0.95),
        state1=np.full(state_shape, (i + 1) % 250, np.float32),
        terminal1=np.float32(i % 2),
    )


def test_feed_sample_roundtrip():
    m = NativeRingReplay(capacity=16, state_shape=(4,),
                         state_dtype=np.float32)
    for i in range(10):
        m.feed(_tr(i))
    assert m.size == 10
    assert m.total_feeds == 10
    rng = np.random.default_rng(0)
    b = m.sample(32, rng)
    # row consistency: state1 == state0 + 1 (mod 250) and reward == state0
    np.testing.assert_allclose(b.state1[:, 0],
                               (b.state0[:, 0] + 1) % 250)
    np.testing.assert_allclose(b.reward, b.state0[:, 0])
    assert set(np.unique(b.action)) <= {0, 1, 2, 3}


def test_circular_wrap():
    m = NativeRingReplay(capacity=8, state_shape=(2,),
                         state_dtype=np.float32)
    for i in range(20):
        m.feed(_tr(i, (2,)))
    assert m.size == 8
    assert m.total_feeds == 20
    b = m.sample(64, np.random.default_rng(1))
    # only the last 8 rows (12..19) survive
    assert b.reward.min() >= 12
    assert b.reward.max() <= 19


def test_uint8_image_rows():
    m = NativeRingReplay(capacity=32, state_shape=(4, 84, 84),
                         state_dtype=np.uint8)
    t = Transition(
        state0=np.full((4, 84, 84), 200, np.uint8), action=np.int32(3),
        reward=np.float32(1.5), gamma_n=np.float32(0.9),
        state1=np.full((4, 84, 84), 90, np.uint8),
        terminal1=np.float32(0.0))
    m.feed(t)
    b = m.sample(4, np.random.default_rng(2))
    assert b.state0.dtype == np.uint8
    assert int(b.state0[0, 0, 0, 0]) == 200
    assert int(b.state1[0, 0, 0, 0]) == 90
    assert float(b.reward[0]) == 1.5


def _writer(mem, start, n):
    for i in range(start, start + n):
        mem.feed(_tr(i, (8,)))


@pytest.mark.slow
@pytest.mark.timeout(240)
def test_multiprocess_hammer():
    """4 writer processes + concurrent reader: every sampled row must be a
    consistent snapshot (reward == state0[0], state1 == state0+1)."""
    m = NativeRingReplay(capacity=512, state_shape=(8,),
                         state_dtype=np.float32)
    ctx = mp.get_context("spawn")
    writers = [ctx.Process(target=_writer, args=(m, w * 1000, 500))
               for w in range(4)]
    for p in writers:
        p.start()
    rng = np.random.default_rng(3)
    torn = 0
    for _ in range(200):
        if m.size == 0:
            continue
        b = m.sample(64, rng)
        ok = np.isclose(b.reward, b.state0[:, 0]) & \
            np.isclose(b.state1[:, 0], (b.state0[:, 0] + 1) % 250)
        torn += int((~ok).sum())
    for p in writers:
        # generous: spawn startup alone can take ~10s on a loaded machine
        p.join(180)
        assert p.exitcode == 0
    assert torn == 0, f"{torn} torn rows observed"
    assert m.total_feeds == 2000


def test_feed_batch():
    m = NativeRingReplay(capacity=64, state_shape=(3,),
                         state_dtype=np.float32)
    n = 10
    ts = Transition(
        state0=np.arange(n * 3, dtype=np.float32).reshape(n, 3),
        action=np.arange(n, dtype=np.int32),
        reward=np.arange(n, dtype=np.float32),
        gamma_n=np.full(n, 0.9, np.float32),
        state1=np.arange(n * 3, dtype=np.float32).reshape(n, 3) + 1,
        terminal1=np.zeros(n, np.float32))
    m.feed_batch(ts)
    assert m.size == n
    b = m.sample(16, np.random.default_rng(4))
    np.testing.assert_allclose(b.state1[:, 0], b.state0[:, 0] + 1)
