"""Test harness config.

Tests run on CPU with 8 virtual XLA devices so mesh/sharding paths
(data-parallel learner, sharded replay) are exercised without TPU hardware —
the strategy SURVEY.md §4 prescribes for the missing reference test layer.
Must set env vars before jax initialises a backend.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some images pre-import jax via sitecustomize with a hardware platform
# pinned (e.g. JAX_PLATFORMS=axon); backends init lazily, so flipping the
# live config before the first jax.devices() call still lands on CPU.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
