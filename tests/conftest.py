"""Test harness config.

Tests run on CPU with 8 virtual XLA devices so mesh/sharding paths
(data-parallel learner, sharded replay) are exercised without TPU hardware —
the strategy SURVEY.md §4 prescribes for the missing reference test layer.
Must set env vars before jax initialises a backend.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some images pre-import jax via sitecustomize with a hardware platform
# pinned (e.g. JAX_PLATFORMS=axon); backends init lazily, so flipping the
# live config before the first jax.devices() call still lands on CPU.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# NO persistent XLA compile cache on the CPU backend, on purpose: XLA's
# CPU AOT loader warns that cached executables were compiled with
# pseudo-features (+prefer-no-gather/-scatter) its host-feature check
# can't match, and for the suite's collective-dense multi-device
# programs (the pp pipeline step above all) the warning is REAL — with
# the cache enabled the AOT-loaded executable nondeterministically
# SIGABRTs the whole pytest process (~25% of runs, reproduced 2026-07-31
# with an 8-run A/B: 3/8 aborts with cache, 0/22 without).  The suite
# pays fresh compiles instead; utils/helpers.enable_compile_cache keeps
# the cache for TPU-platform processes, whose entries are TPU
# executables that never cross the CPU AOT loader.  Enforced, not just
# unset: an ambient env var (e.g. exported by a TPU drive's shell)
# would otherwise silently re-enable it here and in every spawn child.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
jax.config.update("jax_compilation_cache_dir", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import signal  # noqa: E402

import pytest  # noqa: E402

# Per-test wall-clock backstop (SIGALRM; pytest-timeout is not in this
# image).  Unmarked tests get DEFAULT_TIMEOUT; long end-to-end tests carry
# @pytest.mark.slow plus an explicit @pytest.mark.timeout(n).  The fast
# tier is `pytest -m "not slow"`.  Note the alarm can only interrupt the
# main thread between bytecodes: a test stuck inside one long C call
# (e.g. an XLA compile) overshoots until that call returns.
DEFAULT_TIMEOUT = 300


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: end-to-end/learning test excluded from the fast "
        "tier (run with -m slow or no -m filter)")
    config.addinivalue_line(
        "markers", "timeout(seconds): per-test wall-clock limit "
        "(default %d)" % DEFAULT_TIMEOUT)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("timeout")
    limit = int(marker.args[0]) if marker else DEFAULT_TIMEOUT

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"{item.nodeid} exceeded the {limit}s wall-clock limit")

    old_handler = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(limit)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old_handler)
