import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.memory import DeviceReplay
from pytorch_distributed_tpu.utils.experience import Transition


def _chunk(start, n, state_shape=(4,)):
    i = np.arange(start, start + n, dtype=np.float32)
    return Transition(
        state0=np.broadcast_to(i[:, None], (n, *state_shape)).astype(np.float32),
        action=(i % 2).astype(np.int32),
        reward=i.astype(np.float32),
        gamma_n=np.full(n, 0.99, dtype=np.float32),
        state1=np.broadcast_to(i[:, None] + 1, (n, *state_shape)).astype(np.float32),
        terminal1=np.zeros(n, dtype=np.float32),
    )


def test_device_replay_roundtrip():
    m = DeviceReplay(capacity=16, state_shape=(4,), state_dtype=np.float32)
    m.feed_chunk(_chunk(0, 8))
    assert m.size == 8
    b = m.sample(32, jax.random.PRNGKey(0))
    b = jax.tree_util.tree_map(np.asarray, b)
    np.testing.assert_allclose(b.state1[:, 0], b.state0[:, 0] + 1)
    np.testing.assert_allclose(b.reward, b.state0[:, 0])
    assert set(np.unique(b.index)) <= set(range(8))


def test_device_replay_wraparound():
    m = DeviceReplay(capacity=8, state_shape=(2,), state_dtype=np.float32)
    m.feed_chunk(_chunk(0, 6, (2,)))
    m.feed_chunk(_chunk(6, 6, (2,)))  # wraps: slots hold 8..11, 4..7... etc
    assert m.size == 8
    b = jax.tree_util.tree_map(
        np.asarray, m.sample(128, jax.random.PRNGKey(1)))
    present = set(np.unique(b.reward).tolist())
    assert present <= set(float(x) for x in range(4, 12))


def test_device_replay_sharded_over_mesh():
    devs = jax.devices()
    assert len(devs) == 8, "conftest must force 8 cpu devices"
    mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
    m = DeviceReplay(capacity=32, state_shape=(4,), state_dtype=np.float32,
                     mesh=mesh, axis="dp")
    m.feed_chunk(_chunk(0, 16))
    b = jax.tree_util.tree_map(
        np.asarray, m.sample(64, jax.random.PRNGKey(0)))
    np.testing.assert_allclose(b.state1[:, 0], b.state0[:, 0] + 1)
    # buffer rows really are sharded across the mesh
    shard_devs = {s.device for s in m.state.state0.addressable_shards}
    assert len(shard_devs) == 8


def test_device_replay_uint8():
    m = DeviceReplay(capacity=8, state_shape=(4, 84, 84), state_dtype=np.uint8)
    n = 4
    chunk = Transition(
        state0=np.full((n, 4, 84, 84), 200, dtype=np.uint8),
        action=np.zeros(n, dtype=np.int32),
        reward=np.ones(n, dtype=np.float32),
        gamma_n=np.full(n, 0.95, dtype=np.float32),
        state1=np.full((n, 4, 84, 84), 90, dtype=np.uint8),
        terminal1=np.zeros(n, dtype=np.float32))
    m.feed_chunk(chunk)
    b = m.sample(4, jax.random.PRNGKey(0))
    assert b.state0.dtype == jnp.uint8
    assert int(b.state0[0, 0, 0, 0]) == 200


def test_device_ingest_chunks_and_feeds():
    from pytorch_distributed_tpu.memory.device_replay import DeviceReplayIngest

    ing = DeviceReplayIngest(capacity=16, state_shape=(3,),
                             state_dtype=np.float32, chunk_size=4)
    ing.attach()
    feeder = ing.make_feeder(chunk=2)
    for i in range(7):
        feeder.feed(Transition(
            state0=np.full(3, i, np.float32), action=np.int32(i % 2),
            reward=np.float32(i), gamma_n=np.float32(0.9),
            state1=np.full(3, i + 1, np.float32),
            terminal1=np.float32(0.0)))
    feeder.flush()
    # mp.Queue's feeder thread makes puts visible asynchronously; drain
    # until the data lands (the learner loop drains every step anyway)
    import time

    deadline = time.monotonic() + 5.0
    while (ing.size + len(ing._pending) < 7
           and time.monotonic() < deadline):
        ing.drain()
        time.sleep(0.01)
    # 7 fed -> one full chunk of 4 ingested, 3 pending
    assert ing.size == 4
    assert len(ing._pending) == 3
    b = ing.replay.sample(8, jax.random.PRNGKey(1))
    assert np.all(np.asarray(b.index) < 4)


def test_multi_step_dispatch_topology(tmp_path):
    """steps_per_dispatch > 1: K scanned updates per dispatched program;
    clocks/cadences still line up."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        1, memory_type="device", root_dir=str(tmp_path), num_actors=1,
        steps=60, learn_start=16, batch_size=16, memory_size=1024,
        actor_sync_freq=20, param_publish_freq=10, learner_freq=20,
        evaluator_freq=30, early_stop=60, steps_per_dispatch=4,
        visualize=False)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 60
    from pytorch_distributed_tpu.utils.metrics import read_scalars

    tags = {r["tag"] for r in read_scalars(opt.log_dir)}
    assert "learner/critic_loss" in tags


def test_channels_last_ring_matches_nchw_training():
    """NHWC-resident ring + nhwc_input model == NCHW ring + default model:
    same ingested transitions, same sampling keys -> identical sampled
    contents and identical train-step losses (the layout is an internal
    storage detail; factory.device_ring_channels_last wires it)."""
    from pytorch_distributed_tpu.models import DqnCnnModel
    from pytorch_distributed_tpu.ops.losses import (
        build_dqn_train_step, init_train_state, make_optimizer,
    )

    rng = np.random.default_rng(7)
    n, shape = 32, (4, 12, 12)
    chunk = Transition(
        state0=rng.integers(0, 255, (n, *shape)).astype(np.uint8),
        action=rng.integers(0, 4, n).astype(np.int32),
        reward=rng.normal(size=n).astype(np.float32),
        gamma_n=np.full(n, 0.99, np.float32),
        state1=rng.integers(0, 255, (n, *shape)).astype(np.uint8),
        terminal1=(rng.random(n) < 0.2).astype(np.float32),
    )
    key = jax.random.PRNGKey(3)

    losses = {}
    for cl in (False, True):
        ring = DeviceReplay(capacity=n, state_shape=shape,
                            state_dtype=np.uint8, channels_last=cl)
        ring.feed_chunk(chunk)
        batch = jax.tree_util.tree_map(np.asarray,
                                       ring.sample(16, key))
        # same rows drawn regardless of layout...
        assert batch.state0.shape == ((16, 12, 12, 4) if cl
                                      else (16, *shape))
        model = DqnCnnModel(action_space=4, norm_val=255.0,
                            nhwc_input=cl, compute_dtype=jnp.float32)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 12, 12, 4) if cl
                                     else (1, *shape), np.uint8))
        tx = make_optimizer(lr=1e-3)
        state = init_train_state(params, tx)
        step = jax.jit(build_dqn_train_step(model.apply, tx,
                                            target_model_update=10))
        _state, metrics, _td = step(state, ring.sample(16, key))
        losses[cl] = float(metrics["learner/critic_loss"])
    # ...and the training math is layout-invariant (params init from the
    # same seed produce the same tree either way)
    assert losses[False] == pytest.approx(losses[True], rel=1e-5)


def test_channels_last_snapshot_is_nchw():
    """Checkpoints stay layout-independent: a channels-last ring's
    snapshot rolls back to the public NCHW schema and restores into a
    NCHW ring (and vice versa)."""
    rng = np.random.default_rng(11)
    n, shape = 8, (4, 6, 6)
    chunk = Transition(
        state0=rng.integers(0, 255, (n, *shape)).astype(np.uint8),
        action=np.zeros(n, np.int32),
        reward=np.arange(n, dtype=np.float32),
        gamma_n=np.full(n, 0.99, np.float32),
        state1=rng.integers(0, 255, (n, *shape)).astype(np.uint8),
        terminal1=np.zeros(n, np.float32),
    )
    a = DeviceReplay(capacity=n, state_shape=shape, state_dtype=np.uint8,
                     channels_last=True)
    a.feed_chunk(chunk)
    snap = a.snapshot()
    assert snap["state0"].shape == (n, *shape)  # public NCHW schema
    np.testing.assert_array_equal(snap["state0"], chunk.state0)
    b = DeviceReplay(capacity=n, state_shape=shape, state_dtype=np.uint8,
                     channels_last=False)
    b.restore(snap)
    np.testing.assert_array_equal(
        np.asarray(b.state.state0[:n]), chunk.state0)
