import multiprocessing as mp

import numpy as np
import pytest

from pytorch_distributed_tpu.memory import PrioritizedReplay, SharedReplay
from pytorch_distributed_tpu.utils.experience import Transition


def _tr(i, state_shape=(4,), terminal=0.0):
    return Transition(
        state0=np.full(state_shape, i, dtype=np.float32),
        action=np.int32(i % 2),
        reward=np.float32(i),
        gamma_n=np.float32(0.99),
        state1=np.full(state_shape, i + 1, dtype=np.float32),
        terminal1=np.float32(terminal),
    )


def test_shared_replay_feed_sample_roundtrip():
    m = SharedReplay(capacity=10, state_shape=(4,), state_dtype=np.float32)
    assert m.size == 0
    for i in range(5):
        m.feed(_tr(i))
    assert m.size == 5
    b = m.sample(32, np.random.default_rng(0))
    assert b.state0.shape == (32, 4)
    # sampled rows are consistent: state1 == state0 + 1, reward == state0[...,0]
    np.testing.assert_allclose(b.state1[:, 0], b.state0[:, 0] + 1)
    np.testing.assert_allclose(b.reward, b.state0[:, 0])
    assert np.all(b.weight == 1.0)


def test_shared_replay_circular_overwrite():
    m = SharedReplay(capacity=4, state_shape=(2,), state_dtype=np.float32)
    for i in range(6):
        m.feed(_tr(i, state_shape=(2,)))
    assert m.size == 4  # full
    b = m.sample(64, np.random.default_rng(0))
    # slots 0,1 were overwritten by 4,5: values present are 2..5
    present = set(np.unique(b.reward).tolist())
    assert present <= {2.0, 3.0, 4.0, 5.0}
    assert m.total_feeds == 6


def test_shared_replay_uint8_states():
    m = SharedReplay(capacity=8, state_shape=(4, 84, 84), state_dtype=np.uint8)
    t = Transition(
        state0=np.full((4, 84, 84), 200, dtype=np.uint8),
        action=np.int32(3), reward=np.float32(1.0),
        gamma_n=np.float32(0.95),
        state1=np.full((4, 84, 84), 100, dtype=np.uint8),
        terminal1=np.float32(1.0))
    m.feed(t)
    b = m.sample(2, np.random.default_rng(1))
    assert b.state0.dtype == np.uint8
    assert b.state0[0, 0, 0, 0] == 200


def _writer(mem, start, n):
    for i in range(start, start + n):
        mem.feed(_tr(i, state_shape=(2,)))


def test_shared_replay_cross_process():
    # actors in child processes write; parent samples — the reference's
    # core topology (shared_memory.py shared pages across spawn)
    ctx = mp.get_context("spawn")
    m = SharedReplay(capacity=64, state_shape=(2,), state_dtype=np.float32)
    ps = [ctx.Process(target=_writer, args=(m, k * 10, 10)) for k in range(3)]
    for p in ps:
        p.start()
    for p in ps:
        p.join()
    assert m.size == 30
    b = m.sample(100, np.random.default_rng(0))
    np.testing.assert_allclose(b.state1[:, 0], b.state0[:, 0] + 1)


def test_prioritized_replay_weights_and_sampling():
    m = PrioritizedReplay(capacity=16, state_shape=(2,),
                          state_dtype=np.float32, priority_exponent=1.0,
                          importance_weight=1.0)
    for i in range(4):
        m.feed(_tr(i, state_shape=(2,)), priority=float(i + 1))
    rng = np.random.default_rng(0)
    counts = np.zeros(4)
    for _ in range(300):
        b = m.sample(16, rng)
        np.add.at(counts, b.index, 1)
    freq = counts / counts.sum()
    # priorities (after +eps) roughly 1,2,3,4 -> freq ~ i/10
    np.testing.assert_allclose(freq, [0.1, 0.2, 0.3, 0.4], atol=0.02)
    # beta=1 exact IS weights: w_i ~ (N p_i)^-1 normalised by max
    b = m.sample(256, rng)
    w_for_min = b.weight[b.index == 0]
    assert w_for_min.size and np.allclose(w_for_min, 1.0)  # rarest has max weight
    w_for_max = b.weight[b.index == 3]
    assert np.allclose(w_for_max, 0.25, atol=1e-5)


def test_prioritized_update_priorities():
    m = PrioritizedReplay(capacity=8, state_shape=(2,),
                          state_dtype=np.float32, priority_exponent=1.0)
    for i in range(8):
        m.feed(_tr(i, state_shape=(2,)))
    m.update_priorities(np.array([0, 1, 2, 3, 4, 5, 6]), np.zeros(7))
    rng = np.random.default_rng(0)
    b = m.sample(64, rng)
    # slot 7 keeps max priority; others ~eps -> overwhelmingly sample 7
    assert np.mean(b.index == 7) > 0.95


def test_prioritized_new_items_get_max_priority():
    m = PrioritizedReplay(capacity=8, state_shape=(2,),
                          state_dtype=np.float32)
    m.feed(_tr(0, state_shape=(2,)), priority=10.0)
    m.feed(_tr(1, state_shape=(2,)))  # no priority -> max so far
    p0, p1 = m.sum_tree.get(np.array([0, 1]))
    assert p1 >= p0 * 0.99


def test_prioritized_circular():
    m = PrioritizedReplay(capacity=4, state_shape=(2,), state_dtype=np.float32)
    for i in range(7):
        m.feed(_tr(i, state_shape=(2,)))
    assert m.size == 4
    b = m.sample(64, np.random.default_rng(0))
    present = set(np.unique(b.reward).tolist())
    assert present <= {3.0, 4.0, 5.0, 6.0}
