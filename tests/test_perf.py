"""Performance observability plane (ISSUE 6): FLOPs-capture parity with
the bench's counting, MFU math, retrace detection, transfer-audit
attribution, the T_PROFILE verb over a real gateway, the bench
regression gate, the incremental metrics tail reader, the profiling
label/nesting satellites — and the acceptance drill: a short CPU run
with TPU_APEX_PERF=1 exports learner/mfu, learner/updates_per_s,
actor/env_frames_per_s and per-role memory watermarks as metrics rows,
live-readable through fleet_top while a T_PROFILE window captures a
real trace from the running topology."""

import json
import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import bench
from tools import bench_gate
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import PerfParams, build_options
from pytorch_distributed_tpu.parallel.dcn import (
    DcnGateway, fetch_profile, fetch_status,
)
from pytorch_distributed_tpu.utils import perf, profiling, tracing
from pytorch_distributed_tpu.utils import flight_recorder
from pytorch_distributed_tpu.utils.metrics import (
    MetricsWriter, ScalarsTail, read_scalars,
)
from pytorch_distributed_tpu.utils.profiling import StepTimer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _warm_profiler(tmp_path_factory):
    """Pay the XLA profiler's one-time session init (it lazily imports
    the whole tensorflow tree, ~35 s on this image) ONCE, idle, before
    any test here opens a trace window — otherwise whichever test
    captures first pays it mid-drill, GIL-starved behind a busy
    topology, and times out order-dependently.  Production fleets
    amortize the same cost via perf.prewarm_profiler at startup."""
    with profiling.trace("warm", log_dir=str(
            tmp_path_factory.mktemp("profiler_warm"))):
        pass


@pytest.fixture(autouse=True)
def _fresh_perf(monkeypatch):
    """Monitors are a per-process registry (like tracers); isolate each
    test, and strip any perf env an earlier topology exported."""
    for var in list(os.environ):
        if var == "TPU_APEX_PERF" or var.startswith("TPU_APEX_PERF_"):
            monkeypatch.delenv(var, raising=False)
    perf.reset()
    tracing.reset()
    flight_recorder.reset()
    yield
    perf.reset()
    tracing.reset()
    flight_recorder.reset()


# ---------------------------------------------------------------------------
# FLOPs capture parity + MFU math (tentpole part 1)
# ---------------------------------------------------------------------------

class TestFlopsCapture:
    def test_parity_with_bench_counting_on_fused_step(self):
        """utils/perf.flops_of_compiled must extract exactly what the
        bench's inline counting did (the code it deduplicates), on a
        real fused sample+train program."""
        import jax

        fused, state, ring = bench._mlp_fused_program(8, 2)
        keys = jax.random.split(jax.random.PRNGKey(0), 2)
        compiled = fused.lower(state, ring.state, keys).compile()
        # the pre-refactor bench extraction, verbatim
        cost = compiled.cost_analysis()
        c = cost[0] if isinstance(cost, (list, tuple)) else cost
        inline = float((c or {}).get("flops"))
        assert inline > 0
        assert perf.flops_of_compiled(compiled) == inline

    def test_monitor_captures_flops_at_compile_time(self):
        import jax
        import jax.numpy as jnp

        m = perf.PerfMonitor("learner", PerfParams(enabled=True))
        f = jax.jit(lambda x: jnp.dot(x, x))
        flops = m.capture_flops(lambda: f.lower(jnp.ones((16, 16))))
        assert flops and flops > 0
        assert m.flops_per_update == flops

    def test_disabled_monitor_is_inert(self):
        m = perf.PerfMonitor("learner", PerfParams(enabled=False))
        assert m.capture_flops(lambda: 1 / 0) is None  # thunk never runs
        m.note_updates(5)
        m.note_frames(5)
        assert m.drain() == {}

    def test_peak_flops_table(self):
        class _Dev:
            device_kind = "TPU v5 lite"

        assert perf.peak_flops_of(_Dev()) == 197e12

        class _Cpu:
            device_kind = "cpu"

        assert perf.peak_flops_of(_Cpu()) is None

    def test_peak_flops_scales_by_compute_dtype(self):
        """ISSUE-13 satellite: the MFU denominator is dtype-aware — an
        fp32 run scores against the fp32 MXU peak (half the bf16
        table), never the bf16 one."""
        class _Dev:
            device_kind = "TPU v5 lite"

        assert perf.peak_flops_of(_Dev(), "float32") == 197e12 / 2
        assert perf.peak_flops_of(_Dev(), "bfloat16") == 197e12
        # unknown dtypes keep the bf16 figure rather than guessing
        assert perf.peak_flops_of(_Dev(), "int8") == 197e12

        class _Cpu:
            device_kind = "cpu"

        assert perf.peak_flops_of(_Cpu(), "float32") is None

    def test_monitor_mfu_uses_dtype_scaled_peak(self, monkeypatch):
        """A monitor told its role computes in fp32 resolves half the
        bf16 peak; an explicit peak_flops knob is never scaled (the
        operator named the denominator)."""
        class _Dev:
            device_kind = "TPU v5 lite"

        import jax

        monkeypatch.setattr(jax, "devices", lambda: [_Dev()])
        m = perf.PerfMonitor(
            "learner",
            PerfParams(enabled=True, memory_watermarks=False))
        m.enabled = True
        m.set_compute_dtype("float32")
        assert m._peak_flops() == 197e12 / 2
        m2 = perf.PerfMonitor(
            "learner",
            PerfParams(enabled=True, peak_flops=123.0,
                       memory_watermarks=False))
        m2.enabled = True
        m2.set_compute_dtype("float32")
        assert m2._peak_flops() == 123.0


class TestMfuMath:
    def test_rates_and_mfu_units(self):
        """mfu = updates/s * flops/update / peak — pinned with injected
        clocks so the math (not the scheduler) is under test."""
        m = perf.PerfMonitor(
            "learner",
            PerfParams(enabled=True, peak_flops=200.0,
                       memory_watermarks=False))
        m.flops_per_update = 10.0
        first = m.drain(now=100.0)  # anchor; one-time flops row rides it
        assert first.get("learner/flops_per_update") == 10.0
        m.note_updates(50)
        out = m.drain(now=105.0)
        assert out["learner/updates_per_s"] == pytest.approx(10.0)
        assert out["learner/achieved_flops_per_s"] == pytest.approx(100.0)
        assert out["learner/mfu"] == pytest.approx(0.5)

    def test_frames_rate_and_gauges(self):
        m = perf.PerfMonitor("actor-0", PerfParams(
            enabled=True, memory_watermarks=False), prefix="actor")
        m.drain(now=0.0)
        m.note_frames(400)
        m.set_gauge("actor/custom_gauge", 3.5)
        out = m.drain(now=2.0)
        assert out["actor/env_frames_per_s"] == pytest.approx(200.0)
        assert out["actor/custom_gauge"] == 3.5

    def test_watermarks_present_on_cpu_host(self):
        """On CPU device.memory_stats() is None — the host RSS rows
        carry the per-role watermark (the acceptance's CPU leg)."""
        m = perf.PerfMonitor("learner", PerfParams(enabled=True))
        out = m.drain()
        assert out["perf/learner/rss_bytes"] > 0
        assert out["perf/learner/rss_peak_bytes"] >= \
            out["perf/learner/rss_bytes"] * 0.5  # peak is lifetime-wide

    def test_env_resolution_and_status_snapshot(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_PERF", "1")
        monkeypatch.setenv("TPU_APEX_PERF_PEAK_FLOPS", "123.0")
        pp = perf.resolve(PerfParams())
        assert pp.enabled and pp.peak_flops == 123.0
        m = perf.get_monitor("learner")  # params from env alone
        assert m.enabled
        m.note_updates(3)
        m.drain(now=1.0)
        snap = perf.status_snapshot()
        assert snap["learner"]["updates_total"] == 3.0


# ---------------------------------------------------------------------------
# retrace detector (tentpole part 2)
# ---------------------------------------------------------------------------

class TestRetraceDetector:
    def test_fires_on_forced_recompile_and_stays_silent_warm(self):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: x * 2)
        det = perf.RetraceDetector()
        det.register("act", f._cache_size)
        f(jnp.ones(8))
        assert det.check() == []  # first check IS the warmup mark
        for _ in range(3):
            f(jnp.ones(8))  # warm replays: same shape, no compile
        assert det.check() == []
        assert det.retraces == 0
        f(jnp.ones(4))  # shape leak -> forced recompile
        assert det.check() == ["act"]
        assert det.retraces == 1 and det.fired == {"act": 1}
        assert det.check() == []  # counted once; high-water advanced

    def test_none_size_fns_are_skipped(self):
        det = perf.RetraceDetector()
        det.register("server-side", None)
        det.register("batched", lambda: None)
        assert det.check() == [] and det.check() == []

    def test_monitor_exports_retrace_count(self):
        import jax
        import jax.numpy as jnp

        m = perf.PerfMonitor("actor-0", PerfParams(
            enabled=True, memory_watermarks=False), prefix="actor")
        f = jax.jit(lambda x: x + 1)
        m.register_jit("act", f._cache_size)
        f(jnp.ones(8))
        m.note_frames(1)
        m.drain(now=1.0)  # warmup mark (gated on work having happened)
        f(jnp.ones(3))
        m.note_frames(1)
        out = m.drain(now=2.0)
        assert out["perf/actor/retraces"] == 1.0


# ---------------------------------------------------------------------------
# transfer audit (tentpole part 2)
# ---------------------------------------------------------------------------

class TestTransferAudit:
    def test_attributes_deliberate_host_array_on_hot_path(self):
        """The audit's target class of bug: a host numpy array smuggled
        into a jitted dispatch (an implicit H2D transfer per call).  It
        must be flagged, attributed to THIS file, and the call must
        still return the right answer (retried under allow)."""
        import jax
        import jax.numpy as jnp

        aud = perf.TransferAudit()
        g = jax.jit(lambda a, b: a + b)
        xdev = jax.device_put(jnp.ones(3))
        host = np.ones(3, np.float32)  # the deliberate host sync
        out = aud.run(g, xdev, host)
        np.testing.assert_allclose(np.asarray(out), np.full(3, 2.0))
        assert aud.total == 1
        (site,) = aud.sites
        assert "test_perf.py" in site
        assert "transfer" in aud.last_error.lower()

    def test_clean_calls_pass_unflagged(self):
        import jax
        import jax.numpy as jnp

        aud = perf.TransferAudit()
        g = jax.jit(lambda a: a * 2)
        xdev = jax.device_put(jnp.ones(3))
        aud.run(g, xdev)
        assert aud.total == 0 and aud.sites == {}

    def test_non_transfer_errors_propagate(self):
        aud = perf.TransferAudit()
        with pytest.raises(ZeroDivisionError):
            aud.run(lambda: 1 / 0)
        assert aud.total == 0


# ---------------------------------------------------------------------------
# profiling satellites: label sanitization + nested no-op + totals
# ---------------------------------------------------------------------------

class TestProfilingSatellites:
    def test_label_is_sanitized_into_the_trace_path(self, tmp_path):
        assert profiling.sanitize_label("../../etc/passwd") == \
            "etc-passwd"
        assert profiling.sanitize_label("fused step @K=32") == \
            "fused-step-K-32"
        assert profiling.sanitize_label("...") == "trace"
        with profiling.trace("../evil lab",
                             log_dir=str(tmp_path)) as path:
            pass
        assert path == str(tmp_path / "evil-lab")
        assert os.path.realpath(path).startswith(
            os.path.realpath(str(tmp_path)))

    def test_nested_capture_is_warning_plus_noop(self, tmp_path):
        with profiling.trace("outer", log_dir=str(tmp_path)) as outer:
            assert outer is not None
            with pytest.warns(UserWarning, match="already active"):
                with profiling.trace("inner",
                                     log_dir=str(tmp_path)) as inner:
                    assert inner is None  # no-op, outer keeps recording
                    # doubly-nested same-thread capture: must be
                    # another no-op, not a re-acquire deadlock on the
                    # module lock
                    with profiling.trace(
                            "inner2", log_dir=str(tmp_path)) as i2:
                        assert i2 is None
        # the outer window closed cleanly; a fresh capture works again
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            with profiling.trace("after", log_dir=str(tmp_path)) as p:
                assert p is not None

    def test_disabled_trace_yields_none(self, monkeypatch):
        monkeypatch.delenv("TPU_APEX_PROFILE", raising=False)
        with profiling.trace("anything") as path:
            assert path is None

    def test_steptimer_drain_reports_totals(self):
        t = StepTimer("actor")
        t.add("act", 0.010)
        t.add("act", 0.030)
        t.add("env", 0.005)
        out = t.drain()
        assert out["actor/time_act_total_ms"] == pytest.approx(40.0)
        assert out["actor/time_env_total_ms"] == pytest.approx(5.0)
        # totals == mean * calls (the stackable identity)
        assert out["actor/time_act_total_ms"] == pytest.approx(
            out["actor/time_act_ms"] * out["actor/time_act_calls"])
        assert t.drain() == {}


# ---------------------------------------------------------------------------
# incremental metrics tail (fleet_top satellite)
# ---------------------------------------------------------------------------

class TestScalarsTail:
    def test_incremental_reads_remember_offset(self, tmp_path):
        path = tmp_path / "scalars.jsonl"
        tail = ScalarsTail(str(tmp_path))
        assert tail.poll() == []  # no file yet
        with open(path, "w") as f:
            f.write(json.dumps({"tag": "a", "value": 1.0}) + "\n")
        assert [r["tag"] for r in tail.poll()] == ["a"]
        assert tail.poll() == []  # nothing new
        with open(path, "a") as f:
            f.write(json.dumps({"tag": "b", "value": 2.0}) + "\n")
            f.write(json.dumps({"tag": "c", "value": 3.0}) + "\n")
        assert [r["tag"] for r in tail.poll()] == ["b", "c"]

    def test_unterminated_tail_is_not_consumed(self, tmp_path):
        """A writer mid-append leaves a torn trailing line; the tail
        reader must wait for the newline and then deliver the COMPLETE
        row — never half-consume it."""
        path = tmp_path / "scalars.jsonl"
        tail = ScalarsTail(str(tmp_path))
        with open(path, "w") as f:
            f.write(json.dumps({"tag": "a", "value": 1.0}) + "\n")
            f.write('{"tag": "b", "val')  # mid-append
        assert [r["tag"] for r in tail.poll()] == ["a"]
        with open(path, "a") as f:
            f.write('ue": 2.0}\n')
        assert tail.poll() == [{"tag": "b", "value": 2.0}]

    def test_kill_torn_terminated_line_is_skipped(self, tmp_path):
        """A newline-terminated but undecodable line (SIGKILL tore the
        payload, a later writer appended past it) is skipped for good —
        the read_scalars torn-artifact philosophy."""
        path = tmp_path / "scalars.jsonl"
        tail = ScalarsTail(str(tmp_path))
        with open(path, "w") as f:
            f.write('{"tag": "torn", "val\n')
            f.write(json.dumps({"tag": "good", "value": 1.0}) + "\n")
        assert [r["tag"] for r in tail.poll()] == ["good"]

    def test_truncated_file_resets_cursor(self, tmp_path):
        path = tmp_path / "scalars.jsonl"
        tail = ScalarsTail(str(tmp_path))
        with open(path, "w") as f:
            f.write(json.dumps({"tag": "old", "value": 1.0}) + "\n")
            f.write(json.dumps({"tag": "old2", "value": 2.0}) + "\n")
        assert len(tail.poll()) == 2
        with open(path, "w") as f:  # rotation: fresh, shorter file
            f.write(json.dumps({"tag": "fresh", "value": 9.0}) + "\n")
        assert [r["tag"] for r in tail.poll()] == ["fresh"]


# ---------------------------------------------------------------------------
# T_PROFILE verb over a real gateway (tentpole part 3)
# ---------------------------------------------------------------------------

class TestTProfile:
    def _gateway(self, tmp_path, wire_profiler=True):
        clock, stats = GlobalClock(), ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        profiler = None
        if wire_profiler:
            profiler = lambda msg: perf.run_profile_window(  # noqa: E731
                str(tmp_path / "profiles"),
                label=msg.get("label", "t"),
                seconds=msg.get("seconds", 0.2), max_seconds=1.0)
        gw = DcnGateway(store, clock, stats, put_chunk=lambda i: None,
                        host="127.0.0.1", port=0, profiler=profiler)
        return gw

    def test_round_trip_captures_real_trace(self, tmp_path):
        gw = self._gateway(tmp_path)
        try:
            reply = fetch_profile(("127.0.0.1", gw.port), seconds=0.2,
                                  label="accept test")
            assert "error" not in reply, reply
            assert reply["seconds"] == pytest.approx(0.2)
            # the label was sanitized into the path, inside the dir
            assert reply["trace_dir"] == str(
                tmp_path / "profiles" / "accept-test")
            # a REAL xplane landed (jax profiler works on CPU)
            found = []
            for root, _dirs, files in os.walk(reply["trace_dir"]):
                found += [f for f in files if f.endswith(".xplane.pb")]
            assert found, f"no xplane.pb under {reply['trace_dir']}"
            assert gw.profiles_served == 1
            # STATUS stays live on the same gateway
            assert fetch_status(("127.0.0.1", gw.port))["uptime"] >= 0
        finally:
            gw.close()

    def test_seconds_clamped_by_server(self, tmp_path):
        gw = self._gateway(tmp_path)
        try:
            t0 = time.monotonic()
            reply = fetch_profile(("127.0.0.1", gw.port), seconds=300.0)
            assert time.monotonic() - t0 < 30.0  # clamped to max 1.0s
            assert reply["seconds"] == pytest.approx(1.0)
        finally:
            gw.close()

    def test_unwired_gateway_replies_error_not_crash(self, tmp_path):
        gw = self._gateway(tmp_path, wire_profiler=False)
        try:
            reply = fetch_profile(("127.0.0.1", gw.port), seconds=0.1)
            assert "no profiler wired" in reply["error"]
            # the session plane is unharmed
            assert fetch_status(("127.0.0.1", gw.port))["uptime"] >= 0
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# bench regression gate (tentpole part 4)
# ---------------------------------------------------------------------------

def _fixture(updates=400.0, e2e=450.0, overhead=0.005, schema=3):
    return {
        "bench_schema": schema,
        "metric": "dqn_cnn_learner_updates_per_sec",
        "value": updates,
        "device_kind": "cpu",
        "updates_per_sec": updates,
        "families": {"dqn-mlp": {"updates_per_sec": updates * 0.9},
                     "ddpg-mlp": {"updates_per_sec": updates * 0.5}},
        "e2e_frames_per_sec": e2e,
        "health_overhead": {"health_overhead_frac": overhead},
        "smoke": {"updates_per_sec": updates},
    }


class TestBenchGate:
    def test_identical_artifacts_pass(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture()))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(_fixture()))
        rc = bench_gate.main([str(cand), "--against", str(base)])
        assert rc == 0

    def test_doctored_regression_exits_1(self, tmp_path, capsys):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture()))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(_fixture(updates=100.0)))  # -75%
        rc = bench_gate.main([str(cand), "--against", str(base)])
        assert rc == 1
        err = capsys.readouterr().err
        assert "regression" in err

    def test_dip_within_tolerance_passes(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture(updates=400.0)))
        cand = tmp_path / "cand.json"
        # -10% everywhere: inside every relative band
        cand.write_text(json.dumps(_fixture(updates=360.0, e2e=405.0)))
        rc = bench_gate.main([str(cand), "--against", str(base)])
        assert rc == 0

    def test_tolerance_override_tightens_the_gate(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture(updates=400.0)))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(_fixture(updates=360.0, e2e=405.0)))
        rc = bench_gate.main([str(cand), "--against", str(base),
                              "--tol", "micro=0.05"])
        assert rc == 1  # the same -10% now fails the micro section

    def test_overhead_fracs_use_absolute_band(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture(overhead=0.001)))
        cand = tmp_path / "cand.json"
        # 5x "regression" on a noise-floor fraction: inside the 0.02
        # absolute band, not a finding
        cand.write_text(json.dumps(_fixture(overhead=0.005)))
        assert bench_gate.main([str(cand), "--against", str(base)]) == 0
        cand.write_text(json.dumps(_fixture(overhead=0.09)))
        assert bench_gate.main([str(cand), "--against", str(base)]) == 1

    def test_schema_drift_refused_without_flag(self, tmp_path, capsys):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture(schema=2)))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(_fixture(schema=3)))
        rc = bench_gate.main([str(cand), "--against", str(base)])
        assert rc == 2
        assert "bench_schema mismatch" in capsys.readouterr().err
        rc = bench_gate.main([str(cand), "--against", str(base),
                              "--allow-schema-drift"])
        assert rc == 0

    def test_missing_sections_are_skipped_not_failed(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture()))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(
            {"bench_schema": 3, "smoke": {"updates_per_sec": 400.0}}))
        assert bench_gate.main([str(cand), "--against", str(base)]) == 0

    def test_history_records_every_gate_run(self, tmp_path):
        base = tmp_path / "base.json"
        base.write_text(json.dumps(_fixture()))
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(_fixture(updates=100.0)))
        hist = tmp_path / "hist.jsonl"
        bench_gate.main([str(cand), "--against", str(base),
                         "--record", str(hist)])
        bench_gate.main([str(base), "--against", str(base),
                         "--record", str(hist)])
        rows = [json.loads(line) for line in open(hist)]
        assert len(rows) == 2
        assert rows[0]["pass"] is False
        assert "updates_per_sec" in rows[0]["regressions"]
        assert rows[1]["pass"] is True and rows[1]["regressions"] == []

    def test_real_smoke_baseline_gates_itself(self):
        """The checked-in baseline passes against itself (the
        acceptance's '0 on the real baseline' leg) and a doctored copy
        regresses (the '1 on a doctored fixture' leg)."""
        baseline = os.path.join(_REPO, "BENCH_SMOKE_BASELINE.json")
        assert bench_gate.main([baseline, "--against", baseline]) == 0
        doctored = json.load(open(baseline))
        doctored["smoke"]["updates_per_sec"] *= 0.3
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(doctored, f)
        try:
            assert bench_gate.main([f.name, "--against", baseline]) == 1
        finally:
            os.unlink(f.name)


class TestBenchSmokeCI:
    def test_smoke_bench_feeds_the_gate(self, tmp_path):
        """The tier-1-adjacent CI check the satellite asks for:
        ``bench.py --smoke`` output piped into ``bench_gate --against
        BENCH_SMOKE_BASELINE.json`` passes and lands in history.  A
        generous smoke tolerance absorbs host noise; the tight bar is
        same-machine history, not this cross-run check."""
        # strip conftest's forced 8-virtual-device XLA_FLAGS: the
        # checked-in baseline (and every standalone bench/check.sh run)
        # measures the production device profile, and the 8-device
        # replicated anakin leg is ~5x slower on this 2-vCPU host —
        # inheriting the flag gates apples against oranges
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env["XLA_FLAGS"] = " ".join(
            t for t in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in t)
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"), "--smoke"],
            capture_output=True, text=True, timeout=240, env=env)
        assert proc.returncode == 0, proc.stderr[-800:]
        smoke = json.loads(proc.stdout.strip().splitlines()[-1])
        assert smoke["smoke"]["updates_per_sec"] > 0
        assert smoke["smoke"].get("flops_per_update", 0) > 0
        cand = tmp_path / "cand.json"
        cand.write_text(json.dumps(smoke))
        hist = tmp_path / "hist.jsonl"
        rc = bench_gate.main([
            str(cand),
            "--against", os.path.join(_REPO, "BENCH_SMOKE_BASELINE.json"),
            "--tol", "smoke=0.9", "--record", str(hist)])
        assert rc == 0
        row = json.loads(open(hist).read())
        assert row["mode"] == "smoke" and row["pass"] is True

    def test_perf_overhead_section_structure(self):
        """The measurement logic of the new bench ``perf_overhead``
        section, on the CPU-safe smoke geometry (the flagship CNN
        variant is the TPU bench's job; <2% is asserted THERE — a noisy
        1-core host can't hold that bar meaningfully)."""
        out = bench.bench_perf_overhead(windows=2, updates_per_window=32,
                                        smoke=True)["perf_overhead"]
        assert out["updates_per_sec_monitored"] > 0
        assert out["updates_per_sec_bare"] > 0
        assert out["perf_overhead_frac"] is not None
        assert out["perf_overhead_frac"] >= 0.0
        assert out["geometry"] == "smoke-mlp"


# ---------------------------------------------------------------------------
# acceptance: live perf plane on a short CPU run
# ---------------------------------------------------------------------------

class TestPerfPlaneAcceptance:
    def test_short_cpu_run_exports_live_perf_plane(self, tmp_path,
                                                   monkeypatch):
        """ISSUE 6 acceptance: with TPU_APEX_PERF=1, a short CPU run
        exports learner/mfu, learner/updates_per_s,
        actor/env_frames_per_s and per-role memory watermarks as
        metrics rows; fleet_top --json surfaces them live; and
        T_PROFILE captures a real trace from the RUNNING topology."""
        monkeypatch.setenv("TPU_APEX_PERF", "1")
        # CPU has no table peak: the documented override supplies one
        # so the mfu row exists (value = achieved / this peak)
        monkeypatch.setenv("TPU_APEX_PERF_PEAK_FLOPS", "1e12")
        from pytorch_distributed_tpu.fleet import FleetTopology

        opt = build_options(
            1, memory_type="device", root_dir=str(tmp_path),
            refs="perfrun", num_actors=1, seed=3,
            # the test ends the run itself (stop event in the finally)
            # once every probe landed; max_seconds is the backstop.
            # Replay-ratio pacing keeps the learner from churning the
            # GIL at full speed, so the profiler prewarm thread
            # finishes during the run instead of starving behind it.
            steps=10 ** 9, max_seconds=150.0, max_replay_ratio=8.0,
            learn_start=16, memory_size=512, batch_size=16,
            actor_freq=25, actor_sync_freq=100, param_publish_freq=50,
            learner_freq=50, logger_freq=2, evaluator_nepisodes=0,
            early_stop=50, checkpoint_freq=0)
        topo = FleetTopology(opt, local_actors=1, port=0)
        done = threading.Event()

        def run():
            try:
                topo.run(backend="thread")
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        addr = ("127.0.0.1", topo.port)
        try:
            # 1) the live plane: STATUS grows a perf block once the
            # learner's first stats window drains
            status = None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and not done.is_set():
                try:
                    status = fetch_status(addr, timeout=5.0)
                except (ConnectionError, OSError):
                    status = None
                if status and "learner/updates_per_s" in (
                        status.get("perf") or {}).get("learner", {}):
                    break
                time.sleep(0.25)
            assert status is not None and "perf" in status, \
                "perf block never appeared in STATUS"
            lsnap = status["perf"]["learner"]
            assert lsnap["learner/updates_per_s"] > 0
            assert lsnap["learner/mfu"] > 0
            assert lsnap["perf/learner/rss_bytes"] > 0
            assert "actor_frames_per_sec" in status

            # 2) fleet_top --json surfaces the same live block
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "fleet_top.py"),
                 f"127.0.0.1:{topo.port}", "--json"],
                capture_output=True, text=True, timeout=60,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stderr
            seen = json.loads(proc.stdout)
            assert seen["perf"]["learner"]["learner/updates_per_s"] > 0

            # 3) T_PROFILE captures a real trace from the running
            # topology, no restart.  The startup prewarm window
            # (perf.prewarm_profiler) may still hold the one-window
            # lock — a transient busy reply, retried
            deadline = time.monotonic() + 120
            while True:
                reply = fetch_profile(addr, seconds=0.3, label="live")
                if "error" not in reply:
                    break
                assert ("already active" in reply["error"]
                        or "unavailable" in reply["error"]), reply
                assert time.monotonic() < deadline, reply
                time.sleep(0.5)
            found = []
            for root, _d, files in os.walk(reply["trace_dir"]):
                found += [f for f in files if f.endswith(".xplane.pb")]
            assert found, f"no xplane under {reply['trace_dir']}"
        finally:
            topo.clock.stop.set()
            t.join(120)
        assert not t.is_alive()

        # 4) the exported rows: every acceptance tag is a scalar row in
        # the run's metrics stream, role-stamped
        rows = read_scalars(opt.log_dir)
        by_tag = {}
        for r in rows:
            if "value" in r:
                by_tag.setdefault(r["tag"], []).append(r)
        for tag in ("learner/mfu", "learner/updates_per_s",
                    "learner/flops_per_update", "learner/replay_ratio",
                    "actor/env_frames_per_s",
                    "perf/learner/rss_bytes", "perf/learner/rss_peak_bytes",
                    "perf/actor/rss_bytes"):
            assert tag in by_tag, \
                f"{tag} missing (have {sorted(by_tag)[:40]}...)"
        assert any(r["value"] > 0 for r in by_tag["learner/mfu"])
        assert any(r["value"] > 0
                   for r in by_tag["actor/env_frames_per_s"])
        assert by_tag["learner/mfu"][0]["role"] == "learner"
        assert by_tag["perf/actor/rss_bytes"][0]["role"] == "actor-0"
        # the retrace watch ran and stayed silent (no shape leaks in
        # the production hot loops)
        assert all(r["value"] == 0.0
                   for r in by_tag.get("perf/learner/retraces", []))

    def test_fleet_top_metrics_overlay_tails_incrementally(self,
                                                           tmp_path):
        """fleet_top --json --metrics overlays the newest perf rows via
        the incremental tail reader (no gateway-side perf needed)."""
        clock, stats = GlobalClock(), ActorStats()
        store = ParamStore(8)
        store.publish(np.zeros(8, dtype=np.float32))
        gw = DcnGateway(store, clock, stats, put_chunk=lambda i: None,
                        host="127.0.0.1", port=0)
        writer = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                               role="learner", run_id="x")
        writer.scalars({"learner/mfu": 0.17,
                        "perf/learner/rss_bytes": 1e9}, step=5)
        writer.close()
        try:
            proc = subprocess.run(
                [sys.executable,
                 os.path.join(_REPO, "tools", "fleet_top.py"),
                 f"127.0.0.1:{gw.port}", "--json",
                 "--metrics", str(tmp_path)],
                capture_output=True, text=True, timeout=60,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            assert proc.returncode == 0, proc.stderr
            status = json.loads(proc.stdout)
            assert status["metrics_latest"]["learner/mfu"] == 0.17
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# plot_run phase breakdown (StepTimer totals satellite)
# ---------------------------------------------------------------------------

class TestPhaseBreakdownPlot:
    def test_stacked_phase_plot_from_totals(self, tmp_path):
        pytest.importorskip("matplotlib")
        writer = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                               role="actor-0", run_id="x")
        wall = time.time()
        for i in range(4):
            writer.scalars({"actor/time_act_total_ms": 100.0 + i,
                            "actor/time_env_total_ms": 40.0,
                            "actor/time_advance_total_ms": 20.0},
                           step=i, wall=wall + 10 * i)
        writer.close()
        out = tmp_path / "phases.png"
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "plot_run.py"),
             str(tmp_path), "--phase-breakdown", "actor",
             "--out", str(out)],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "MPLBACKEND": "Agg"})
        assert proc.returncode == 0, proc.stderr
        assert out.exists() and out.stat().st_size > 0

    def test_multi_process_roles_need_an_exact_role(self, tmp_path):
        """Two actor processes share the ``actor/`` tag prefix; a bare
        --phase-breakdown actor would interleave their unrelated drain
        windows, so it must refuse and name them — an exact role plots
        that process only."""
        wall = time.time()
        for role in ("actor-0", "actor-1"):
            writer = MetricsWriter(str(tmp_path),
                                   enable_tensorboard=False, role=role,
                                   run_id="x")
            for i in range(3):
                writer.scalars({"actor/time_act_total_ms": 50.0,
                                "actor/time_env_total_ms": 10.0},
                               step=i, wall=wall + 10 * i + 0.1)
            writer.close()
        from tools import plot_run

        with pytest.raises(SystemExit, match="actor-0, actor-1"):
            plot_run.load_phase_windows(str(tmp_path), "actor")
        walls, phases = plot_run.load_phase_windows(str(tmp_path),
                                                    "actor-1")
        assert len(walls) == 3 and set(phases) == {"act", "env"}

    def test_missing_rows_fail_loudly(self, tmp_path):
        pytest.importorskip("matplotlib")
        writer = MetricsWriter(str(tmp_path), enable_tensorboard=False)
        writer.scalar("learner/critic_loss", 1.0, step=0)
        writer.close()
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "plot_run.py"),
             str(tmp_path), "--phase-breakdown", "actor"],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "MPLBACKEND": "Agg"})
        assert proc.returncode != 0
        assert "time_*_total_ms" in proc.stderr


# ---------------------------------------------------------------------------
# ISSUE 7: the fused device rollout on the perf plane — retrace
# fire/silent drill + transfer-audit coverage of the new hot program,
# and the fleet_top per-actor panel line
# ---------------------------------------------------------------------------

class TestDeviceRolloutPerfPlane:
    @pytest.fixture(scope="class")
    def rollout(self):
        """A tiny fused rollout (linear policy, 2 device Pong envs)
        shared by the drills — the registration surface is identical
        to the production CNN one."""
        import jax.numpy as jnp

        from pytorch_distributed_tpu.envs.device_env import (
            build_device_env,
        )
        from pytorch_distributed_tpu.models.policies import (
            build_fused_rollout, init_rollout_carry,
        )

        opt = build_options(4)
        env = build_device_env(opt.env_params, 0, 2)
        dim = int(np.prod(env.state_shape))
        w = jnp.asarray(np.zeros((dim, 6), np.float32))

        def apply_fn(params, obs):
            return obs.reshape((obs.shape[0], -1)).astype(
                jnp.float32) @ params

        roll = build_fused_rollout(apply_fn, env, nstep=2, gamma=0.99,
                                   rollout_ticks=2, emit="chunk")
        return dict(roll=roll, w=w, env=env,
                    carry=lambda: init_rollout_carry(env, 2))

    def test_rollout_retrace_drill_silent_then_fires(self, rollout):
        """The registered rollout program must stay silent across
        same-shape dispatches (the production stream: tick0 is traced,
        so consecutive dispatches share one compile) and FIRE when a
        dtype leak forces a recompile."""
        import jax.numpy as jnp

        roll, w = rollout["roll"], rollout["w"]
        m = perf.PerfMonitor("actor-drill", PerfParams(
            enabled=True, memory_watermarks=False), prefix="actor")
        m.register_jit("device_rollout", roll._cache_size)
        key = jnp.asarray(np.zeros(2, np.uint32))
        eps = jnp.zeros((2,), jnp.float32)
        carry, _ = roll(w, rollout["carry"](), key, jnp.int32(0), eps)
        m.note_frames(4)
        m.drain(now=1.0)  # warmup mark
        for d in range(1, 4):  # production stream: traced tick0 only
            carry, _ = roll(w, carry, key, jnp.int32(d * 2), eps)
        m.note_frames(12)
        out = m.drain(now=2.0)
        assert out["perf/actor/retraces"] == 0.0
        # the leak class the detector exists for: a raw python int
        # tick0 (weak-typed i32) instead of the driver's device-
        # resident strong i32 — new aval, fresh trace
        carry, _ = roll(w, carry, key, 8, eps)
        m.note_frames(4)
        out = m.drain(now=3.0)
        assert out["perf/actor/retraces"] == 1.0

    def test_rollout_transfer_audit_clean_and_flagged(self, rollout):
        """The device actor's dispatch is transfer-free by
        construction (device-resident key/eps/tick0/carry): the audit
        must pass it clean, and must flag + attribute + survive a
        smuggled host array."""
        import jax.numpy as jnp

        roll, w = rollout["roll"], rollout["w"]
        aud = perf.TransferAudit()
        key = jnp.asarray(np.zeros(2, np.uint32))
        eps = jnp.zeros((2,), jnp.float32)
        tick0 = jnp.int32(0)
        carry, _ = roll(w, rollout["carry"](), key, tick0, eps)
        carry, _ = aud.run(roll, w, carry, key, tick0 + 2, eps)
        assert aud.total == 0
        # a host numpy eps is an implicit H2D on the audited path
        carry, chunk = aud.run(roll, w, carry, key, tick0 + 4,
                               np.zeros(2, np.float32))
        assert aud.total == 1 and len(aud.sites) == 1
        assert chunk.valid.shape == (2, 2)

    def test_fleet_top_renders_per_actor_backend_line(self):
        """ISSUE 7 satellite: the STATUS ``actors`` block (per-slot env
        frames/s + active backend) renders in the panel and survives
        --json serialization."""
        from tools import fleet_top

        status = {
            "wall": 0.0, "learner_step": 10, "actor_step": 400,
            "slots": {},
            "actors": {
                "0": {"env_frames_per_sec": 512.5, "backend": "device"},
                "1": {"env_frames_per_sec": 100.0, "backend": "device"},
            },
        }
        line = fleet_top.actor_line(status)
        assert "actors[device]" in line
        assert "a0 512.5 f/s" in line and "a1 100 f/s" in line
        panel = fleet_top.render(status)
        assert "actors[device]" in panel
        json.loads(json.dumps(status))  # --json path serializes
        # mixed backends are labelled, absent block renders nothing
        status["actors"]["1"]["backend"] = "pipelined"
        assert "actors[mixed]" in fleet_top.actor_line(status)
        assert fleet_top.actor_line({"slots": {}}) is None
