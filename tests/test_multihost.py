"""init_multihost (parallel/mesh.py): the learner-spans-hosts path.

A real 2-process jax.distributed cluster on the CPU backend — the same
``jax.distributed.initialize`` call a TPU pod makes (there: one process
per host, coordinator on host 0), validated end-to-end: cluster formation,
global device visibility, the standard mesh over all processes' devices,
and one jitted cross-process reduction.  SURVEY.md §5 "distributed
communication backend"."""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.timeout(600)
def test_init_multihost_two_process_cpu_cluster():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # append, never overwrite: the default PYTHONPATH carries the
    # hardware-platform plugin site dir
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _CHILD, coordinator, "2", str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"process {pid} exited {p.returncode}:\n{out[-3000:]}")
        assert "MULTIHOST_OK 18.0" in out, out[-3000:]
