"""Contract tests for the import-gated envs (ALE Atari, gym adapter).

Neither ale_py/atari_py nor gymnasium/gym ships in this image, so the
field-risk in envs/atari.py::_load_ale (both branches) and
envs/gym_adapter.py is exercised here against minimal fakes installed in
``sys.modules`` — proving the adapter logic (seeding calls, sticky-action
and frame-cap settings, frame pipeline, life-loss semantics, action
rescaling, truncation mapping) without the real wheels, per the reference
contract (reference core/envs/atari_env.py:19-28, 89-129)."""

import sys
import types

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options


# ---------------------------------------------------------------------------
# fakes
# ---------------------------------------------------------------------------


class FakeALE:
    """Deterministic stand-in for ale_py/atari_py's ALEInterface.

    Screen is a 210x160 gradient keyed on the frame counter; 3 lives, one
    lost every 40 acts; game over after 2 lost lives (so life-loss and
    game-over are distinct events).  Records every set* call so tests can
    assert the construction contract.
    """

    WIDTH, HEIGHT = 160, 210

    def __init__(self, byte_keys: bool, flat_screen: bool):
        self.byte_keys = byte_keys
        self.flat_screen = flat_screen
        self.settings = {}
        self.rom = None
        self.frames = 0
        self._lives = 3

    # -- settings ----------------------------------------------------------
    def _key(self, key):
        expected = bytes if self.byte_keys else str
        assert isinstance(key, expected), (
            f"ALE settings key must be {expected.__name__}, got {key!r}")
        return key.decode() if isinstance(key, bytes) else key

    def setInt(self, key, value):
        self.settings[self._key(key)] = int(value)

    def setFloat(self, key, value):
        self.settings[self._key(key)] = float(value)

    def loadROM(self, rom):
        self.rom = rom

    # -- game --------------------------------------------------------------
    def getMinimalActionSet(self):
        return [0, 1, 3, 4]  # pong-like minimal set

    def reset_game(self):
        self.frames = 0
        self._lives = 3

    def act(self, action):
        assert action in self.getMinimalActionSet()
        self.frames += 1
        if self.frames % 40 == 0:
            self._lives -= 1
        return 1.0 if self.frames % 8 == 0 else 0.0

    def lives(self):
        return self._lives

    def game_over(self):
        return self._lives <= 1

    def getScreenDims(self):
        return (self.WIDTH, self.HEIGHT)  # ALE convention: (width, height)

    def getScreenGrayscale(self):
        row = (np.arange(self.HEIGHT, dtype=np.uint8) + self.frames)
        screen = np.repeat(row[:, None], self.WIDTH, axis=1)
        return screen.ravel() if self.flat_screen else screen


def _fake_ale_py(made):
    """A fake ``ale_py`` (str keys, 2-D screens, roms.get_rom_path)."""
    mod = types.ModuleType("ale_py")

    def ALEInterface():
        ale = FakeALE(byte_keys=False, flat_screen=False)
        made.append(ale)
        return ale

    mod.ALEInterface = ALEInterface
    mod.roms = types.SimpleNamespace(
        get_rom_path=lambda game: f"/roms/{game}.bin")
    return mod


def _fake_atari_py(made):
    """A fake legacy ``atari_py`` (byte keys, flat screens,
    get_game_path)."""
    mod = types.ModuleType("atari_py")

    def ALEInterface():
        ale = FakeALE(byte_keys=True, flat_screen=True)
        made.append(ale)
        return ale

    mod.ALEInterface = ALEInterface
    mod.get_game_path = lambda game: f"/roms/{game}.bin"
    return mod


@pytest.fixture
def no_ale(monkeypatch):
    monkeypatch.setitem(sys.modules, "ale_py", None)
    monkeypatch.setitem(sys.modules, "atari_py", None)


# ---------------------------------------------------------------------------
# ALE branch tests
# ---------------------------------------------------------------------------


def _atari_env(config=0, **overrides):
    from pytorch_distributed_tpu.envs.atari import AtariEnv

    opt = build_options(config, **overrides)
    return AtariEnv(opt.env_params, process_ind=0)


def test_ale_py_branch_constructs_and_steps(monkeypatch, no_ale):
    made = []
    monkeypatch.setitem(sys.modules, "ale_py", _fake_ale_py(made))
    env = _atari_env()
    ale = made[0]
    # construction contract (reference atari_env.py:20-28)
    assert ale.settings["random_seed"] == env.seed
    assert ale.settings["repeat_action_probability"] == 0.0
    assert ale.settings["max_num_frames_per_episode"] == 12500
    assert ale.rom == "/roms/pong.bin"
    assert env.action_space.n == 4

    obs = env.reset()
    assert obs.shape == (4, 84, 84) and obs.dtype == np.uint8
    obs2, reward, terminal, info = env.step(1)
    assert obs2.shape == (4, 84, 84)
    assert ale.frames >= 4  # action repeat advanced 4 raw frames
    assert "lives" in info
    # frame stack rolled: newest slice differs from a fresh reset's
    assert not np.array_equal(obs2[-1], obs[-1])


def test_atari_py_fallback_branch(monkeypatch, no_ale):
    """ale_py absent -> legacy atari_py branch: byte-string setting keys
    and 1-D screens reshaped via getScreenDims()[::-1]."""
    made = []
    monkeypatch.setitem(sys.modules, "atari_py", _fake_atari_py(made))
    env = _atari_env()
    assert made[0].settings["random_seed"] == env.seed
    obs = env.reset()
    assert obs.shape == (4, 84, 84)
    # the gradient runs down rows: resized rows must be monotonic, which
    # only holds if the flat screen was reshaped (height, width)
    col = obs[-1][:, 0].astype(int)
    assert (np.diff(col) >= 0).all() and col[-1] > col[0]


def test_action_repeat_breaks_at_game_over(monkeypatch, no_ale):
    """The 4x action-repeat loop must stop acting once the emulator
    reports game over — the reference breaks mid-repeat (reference
    atari_env.py:101-103); acting past terminal feeds post-death frames
    into the final max-pool."""
    made = []
    monkeypatch.setitem(sys.modules, "ale_py", _fake_ale_py(made))
    env = _atari_env()
    env.eval()  # standard terminals: game_over only
    env.reset()
    ale = made[0]
    # place the emulator two raw frames before its game-over boundary
    # (FakeALE: a life lost every 40 acts; game over below 2 lives)
    ale.frames, ale._lives = 78, 2
    _obs, _r, terminal, _info = env.step(1)
    assert terminal
    assert ale.frames == 80  # 2 acts, then break — never 4


def test_missing_ale_raises_actionable_error(no_ale):
    with pytest.raises(ImportError, match="pong-sim"):
        _atari_env()


def test_life_loss_is_terminal_only_in_training(monkeypatch, no_ale):
    made = []
    monkeypatch.setitem(sys.modules, "ale_py", _fake_ale_py(made))
    env = _atari_env()
    env.train()
    env.reset()
    ale = made[0]
    ale.frames = 38  # 2 acts from a life loss; 4-repeat crosses it
    _, _, terminal, _ = env.step(0)
    assert terminal and env.just_died
    # resume-by-noop: reset after a life loss keeps the game running
    frames_before = ale.frames
    env.reset()
    assert ale.frames == frames_before + 1  # one no-op, no reset_game
    # eval mode: same situation is NOT terminal
    env2 = _atari_env()
    env2.eval()
    env2.reset()
    ale2 = made[-1]
    ale2.frames = 38
    _, _, terminal, _ = env2.step(0)
    assert not terminal


def test_factory_builds_atari_configs_with_fake_ale(monkeypatch, no_ale):
    """CONFIGS rows 0 (shared) and 7 (PER) construct through the factory
    with an ALE backend present."""
    from pytorch_distributed_tpu.factory import build_env

    made = []
    monkeypatch.setitem(sys.modules, "ale_py", _fake_ale_py(made))
    for config in (0, 7):
        opt = build_options(config)
        env = build_env(opt, process_ind=0)
        obs = env.reset()
        assert obs.shape == (4, 84, 84), f"config {config}"


# ---------------------------------------------------------------------------
# gym adapter fakes + tests
# ---------------------------------------------------------------------------


class _Box:
    def __init__(self, low, high, shape):
        self.low = np.full(shape, low, np.float32)
        self.high = np.full(shape, high, np.float32)
        self.shape = shape


class FakeGymEnv:
    """Continuous-control fake: obs = [step count, last action...]."""

    def __init__(self, modern: bool, truncate_at: int = 25):
        self.modern = modern
        self.truncate_at = truncate_at
        self.observation_space = _Box(-np.inf, np.inf, (3,))
        self.action_space = _Box(-2.0, 2.0, (1,))
        self.n = 0
        self.seeds = []
        self.actions = []

    def seed(self, seed):  # legacy surface
        self.seeds.append(seed)

    def _obs(self):
        last = self.actions[-1] if self.actions else np.zeros(1)
        return np.array([self.n, float(np.ravel(last)[0]), 0.0], np.float32)

    def reset(self, seed=None):
        self.n = 0
        if self.modern:
            self.seeds.append(seed)
            return self._obs(), {}
        return self._obs()

    def step(self, action):
        self.n += 1
        self.actions.append(np.asarray(action))
        truncated = self.n >= self.truncate_at
        if self.modern:
            return self._obs(), 1.0, False, truncated, {}
        info = {"TimeLimit.truncated": True} if truncated else {}
        return self._obs(), 1.0, truncated, info


def _fake_gym_module(name, modern, made):
    mod = types.ModuleType(name)

    def make(env_id):
        made.append((env_id, FakeGymEnv(modern)))
        return made[-1][1]

    mod.make = make
    return mod


@pytest.fixture
def no_gym(monkeypatch):
    monkeypatch.setitem(sys.modules, "gymnasium", None)
    monkeypatch.setitem(sys.modules, "gym", None)


def _gym_env(config=9, **overrides):
    from pytorch_distributed_tpu.envs.gym_adapter import GymEnv

    opt = build_options(config, **overrides)
    return GymEnv(opt.env_params, process_ind=0)


def test_gymnasium_branch_rescales_and_truncates(monkeypatch, no_gym):
    made = []
    monkeypatch.setitem(sys.modules, "gymnasium",
                        _fake_gym_module("gymnasium", True, made))
    env = _gym_env(9)  # halfcheetah row
    assert made[0][0] == "HalfCheetah-v4"
    assert env.state_shape == (3,)
    obs = env.reset()
    assert obs.dtype == np.float32
    fake = made[0][1]
    assert fake.seeds and fake.seeds[0] is not None  # reset(seed=...) used
    # [-1,1] policy action rescales into the env's [-2,2] box
    _, r, terminal, info = env.step(np.array([0.5], np.float32))
    np.testing.assert_allclose(fake.actions[-1], [1.0])
    assert r == 1.0 and not terminal
    # time-limit: terminal with the truncated flag for bootstrap-through
    for _ in range(fake.truncate_at - 1):
        _, _, terminal, info = env.step(np.array([0.0], np.float32))
    assert terminal and info.get("truncated") is True


def test_legacy_gym_branch(monkeypatch, no_gym):
    made = []
    monkeypatch.setitem(sys.modules, "gym",
                        _fake_gym_module("gym", False, made))
    env = _gym_env(2, env_type="gym")  # pendulum row through the adapter
    fake = made[0][1]
    assert made[0][0] == "Pendulum-v1"
    env.reset()
    assert fake.seeds  # legacy .seed() path used
    _, _, terminal, info = env.step(np.array([-0.5], np.float32))
    np.testing.assert_allclose(fake.actions[-1], [-1.0])
    # legacy TimeLimit.truncated maps to the standard flag
    for _ in range(fake.truncate_at - 1):
        _, _, terminal, info = env.step(np.array([0.0], np.float32))
    assert terminal and info.get("truncated") is True


def test_missing_gym_raises_actionable_error(no_gym):
    with pytest.raises(ImportError, match="self-contained"):
        _gym_env(9)


def test_factory_builds_gym_configs_with_fake_gym(monkeypatch, no_gym):
    """CONFIGS rows 9/10 (BASELINE configs 4/5) construct through the
    factory with a gym backend present."""
    from pytorch_distributed_tpu.factory import build_env

    made = []
    monkeypatch.setitem(sys.modules, "gymnasium",
                        _fake_gym_module("gymnasium", True, made))
    for config in (9, 10):
        opt = build_options(config)
        env = build_env(opt, process_ind=0)
        obs = env.reset()
        assert obs.dtype == np.float32, f"config {config}"
