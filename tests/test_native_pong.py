"""C++ batched Pong stepper (native/pong_batch.cpp) vs the Python simulator.

Dynamics between scoring events are deterministic doubles in both
implementations, so the equivalence test sets identical game state on both
and requires bit-exact frames/rewards step for step.  RNG only enters at
ball resets (scoring/reset), which the chosen initial state avoids.
"""

from __future__ import annotations

import numpy as np
import pytest

from pytorch_distributed_tpu.config import EnvParams
from pytorch_distributed_tpu.envs.pong_sim import PongSimEnv

try:
    from pytorch_distributed_tpu.envs.native_pong import (
        NativePongVectorEnv, get_lib,
    )

    get_lib()
    HAVE_NATIVE = True
except Exception:  # noqa: BLE001 - no toolchain in this image
    HAVE_NATIVE = False

pytestmark = pytest.mark.skipif(not HAVE_NATIVE,
                                reason="native toolchain unavailable")


def params(**kw) -> EnvParams:
    base = dict(env_type="pong-sim", seed=7, state_cha=4,
                early_stop=12500, action_repetition=4)
    base.update(kw)
    return EnvParams(**base)


def test_shapes_dtypes_and_reset():
    env = NativePongVectorEnv(params(), process_ind=0, num_envs=3)
    obs = env.reset()
    assert obs.shape == (3, 4, 84, 84) and obs.dtype == np.uint8
    assert env.state_shape == (4, 84, 84)
    assert env.action_space.n == 6
    assert env.norm_val == 255.0
    # reset fills the stack with the first frame
    for i in range(3):
        for k in range(1, 4):
            np.testing.assert_array_equal(obs[i, 0], obs[i, k])
    # background + two paddles + ball are present
    vals = set(np.unique(obs[0, 0]).tolist())
    assert {35, 130, 150, 236} <= vals


def test_determinism_and_seed_diversity():
    a = NativePongVectorEnv(params(), 0, 2)
    b = NativePongVectorEnv(params(), 0, 2)
    c = NativePongVectorEnv(params(), 1, 2)  # different seed slots
    oa, ob, oc = a.reset(), b.reset(), c.reset()
    np.testing.assert_array_equal(oa, ob)
    assert not np.array_equal(oa, oc)
    rng = np.random.default_rng(0)
    for _ in range(20):
        acts = rng.integers(0, 6, size=2)
        oa = a.step(acts)[0]
        ob = b.step(acts)[0]
        np.testing.assert_array_equal(oa, ob)
    # the two envs inside one batch evolve independently
    assert not np.array_equal(oa[0], oa[1])


def test_bit_exact_vs_python_sim():
    """Same state + same actions -> identical frames and rewards."""
    py = PongSimEnv(params(), process_ind=0)
    py.reset()
    nat = NativePongVectorEnv(params(), 0, 1)
    nat.reset()

    # a mid-court rally state: ball heading to the enemy with spin; no
    # scoring for the horizon below, so no RNG enters on either side
    py.player_y, py.enemy_y = 30.0, 55.0
    py.ball_x, py.ball_y = 42.0, 40.0
    py.ball_vx, py.ball_vy = -1.4, 0.3
    py._score = [0, 0]
    nat.set_state(0, np.array([30.0, 55.0, 42.0, 40.0, -1.4, 0.3, 0, 0]))

    frame_py = py._draw()
    np.testing.assert_array_equal(frame_py, nat.render_frame(0))

    actions = [2, 3, 0, 5, 4, 1, 2, 2, 3, 0, 1, 4]
    for t, a in enumerate(actions):
        obs_py, r_py, term_py, _ = py.step(a)
        obs_n, r_n, term_n, _ = nat.step([a])
        assert r_py == 0.0 and r_n[0] == 0.0, "scoring would desync RNG"
        assert not term_py and not term_n[0]
        # the newest frame depends only on dynamics; after state_cha steps
        # the full stacks coincide
        np.testing.assert_array_equal(obs_py[-1], obs_n[0, -1])
        if t >= 3:
            np.testing.assert_array_equal(obs_py, obs_n[0])


def test_autoreset_and_truncation():
    env = NativePongVectorEnv(params(early_stop=3), 0, 2)
    env.reset()
    for t in range(3):
        obs, rew, term, infos = env.step([0, 0])
    assert term.all()
    for i in range(2):
        assert infos[i].get("truncated") is True
        assert "final_obs" in infos[i]
        # returned obs is the RESET observation (stack of one frame),
        # not the terminal one
        for k in range(1, 4):
            np.testing.assert_array_equal(obs[i, 0], obs[i, k])
        assert not np.array_equal(infos[i]["final_obs"], obs[i])
    # episode counter restarted: next step is not terminal again
    _, _, term, _ = env.step([0, 0])
    assert not term.any()


def test_game_end_on_truncation_step_still_flags_truncated():
    """Game point #21 landing exactly on the early_stop step must report
    truncated=True like the Python path (envs/base.py flags the budget hit
    unconditionally) — recurrent actors read it for bootstrap-vs-terminal."""
    env = NativePongVectorEnv(params(early_stop=5), 0, 1)
    env.reset()
    for _ in range(4):
        env.step([0])
    # 5th step: ball about to cross the enemy goal line, player at 20
    # points, enemy paddle parked far away -> scoring + win this step
    env.set_state(0, np.array([42.0, 10.0, 2.0, 70.0, -1.4, 0.0, 0, 20,
                               4, 0]))
    _, rew, term, infos = env.step([0])
    assert rew[0] == 1.0 and term[0]
    assert infos[0]["score"] == (0, 21)
    assert infos[0].get("truncated") is True


def test_noop_policy_loses_to_tracker():
    env = NativePongVectorEnv(params(early_stop=0), 0, 1)
    env.reset()
    total, done = 0.0, False
    for _ in range(20000):
        _, rew, term, infos = env.step([0])
        total += float(rew[0])
        if term[0]:
            done = True
            break
    assert done, "NOOP game must reach 21 points"
    assert total <= -15, f"static paddle should lose badly, got {total}"


def test_state_roundtrip():
    env = NativePongVectorEnv(params(), 0, 1)
    env.reset()
    s = env.get_state(0)
    env.step([3])
    assert not np.allclose(env.get_state(0), s)
    env.set_state(0, s)
    np.testing.assert_allclose(env.get_state(0), s)


def test_factory_routes_to_native():
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import build_env_vector

    opt = build_options(config=4)  # pong-sim row
    opt.env_params.num_envs_per_actor = 2
    env = build_env_vector(opt, process_ind=0, num_envs=2)
    assert type(env).__name__ == "NativePongVectorEnv"
    obs = env.reset()
    assert obs.shape == (2, opt.env_params.state_cha, 84, 84)
    # opting out routes back to the Python vector env
    opt.env_params.native_env = False
    env = build_env_vector(opt, process_ind=0, num_envs=2)
    assert type(env).__name__ == "VectorEnv"
