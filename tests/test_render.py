"""Headless render path (utils/render.py): frame extraction + PNG dumps
through the env render() surface — the capability standing in for the
reference's cv2.imshow display (reference core/env.py:51-76)."""

import glob
import os

import numpy as np

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.utils.render import FrameDumper, frame_image


def test_frame_image_shapes():
    stack = np.arange(4 * 8 * 8, dtype=np.uint8).reshape(4, 8, 8)
    np.testing.assert_array_equal(frame_image(stack), stack[-1])
    gray = stack[0]
    np.testing.assert_array_equal(frame_image(gray), gray)
    rgb = np.zeros((8, 8, 3), np.uint8)
    assert frame_image(rgb).shape == (8, 8, 3)
    assert frame_image(np.zeros(6, np.float32)) is None  # low-dim obs


def test_pong_sim_render_dumps_pngs(tmp_path):
    from pytorch_distributed_tpu.envs.pong_sim import PongSimEnv

    opt = build_options(4)
    env = PongSimEnv(opt.env_params, process_ind=0)
    env.attach_renderer(FrameDumper(str(tmp_path)))
    env.reset()
    env.render()
    for a in (2, 3, 0):
        env.step(a)
        env.render()
    ep0 = sorted(glob.glob(os.path.join(str(tmp_path), "ep000", "*.png")))
    assert len(ep0) == 4
    from PIL import Image

    img = np.asarray(Image.open(ep0[-1]))
    assert img.shape == (84, 84) and img.dtype == np.uint8
    # a second episode lands in its own directory
    env.reset()
    env.render()
    assert glob.glob(os.path.join(str(tmp_path), "ep001", "*.png"))


def test_render_is_noop_without_renderer(tmp_path):
    from pytorch_distributed_tpu.envs.fake_env import FakeChainEnv

    opt = build_options(1)
    env = FakeChainEnv(opt.env_params, process_ind=0)
    env.reset()
    env.render()  # must not raise or write anything
