import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_tpu.models import DdpgMlpModel, DqnMlpModel
from pytorch_distributed_tpu.ops.losses import (
    TrainState, build_ddpg_train_step, build_ddpg_train_step_coupled,
    build_dqn_train_step, init_ddpg_train_state, init_train_state,
    make_optimizer, merge_ddpg_params, split_ddpg_params,
)
from pytorch_distributed_tpu.parallel import ShardedLearner, make_mesh
from pytorch_distributed_tpu.utils.experience import Batch


def _dqn_setup(num_actions=3, obs_dim=4, lr=1e-2, **step_kw):
    model = DqnMlpModel(action_space=num_actions, hidden_dim=32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, obs_dim)))
    tx = make_optimizer(lr)
    state = init_train_state(params, tx)
    step = build_dqn_train_step(model.apply, tx, **step_kw)
    return model, state, step


def _batch(B=16, obs_dim=4, num_actions=3, seed=0, weight=None):
    rng = np.random.default_rng(seed)
    return Batch(
        state0=rng.normal(size=(B, obs_dim)).astype(np.float32),
        action=rng.integers(0, num_actions, size=B).astype(np.int32),
        reward=rng.normal(size=B).astype(np.float32),
        gamma_n=np.full(B, 0.95, dtype=np.float32),
        state1=rng.normal(size=(B, obs_dim)).astype(np.float32),
        terminal1=(rng.random(B) < 0.3).astype(np.float32),
        weight=np.ones(B, np.float32) if weight is None else weight,
        index=np.arange(B, dtype=np.int32),
    )


def test_dqn_step_loss_matches_hand_computed():
    model, state, step = _dqn_setup()
    b = _batch()
    new_state, metrics, td_abs = jax.jit(step)(state, b)
    # hand-compute the loss with numpy against the same initial params
    q = np.asarray(model.apply(state.params, b.state0))
    q_sel = q[np.arange(16), b.action]
    qn = np.asarray(model.apply(state.params, b.state1))  # target==online at t0
    target = b.reward + b.gamma_n * qn.max(1) * (1 - b.terminal1)
    want = np.mean((q_sel - target) ** 2)  # nn.MSELoss parity
    np.testing.assert_allclose(float(metrics["learner/critic_loss"]), want,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(td_abs), np.abs(q_sel - target),
                               rtol=1e-4, atol=1e-5)
    assert int(new_state.step) == 1


def test_dqn_terminal_masks_bootstrap():
    model, state, step = _dqn_setup()
    b = _batch()
    b = b._replace(terminal1=np.ones_like(b.terminal1))
    _, metrics, td_abs = jax.jit(step)(state, b)
    q = np.asarray(model.apply(state.params, b.state0))
    q_sel = q[np.arange(16), b.action]
    np.testing.assert_allclose(np.asarray(td_abs), np.abs(q_sel - b.reward),
                               rtol=1e-4, atol=1e-5)


def test_double_dqn_uses_online_argmax():
    model, state, step = _dqn_setup(enable_double=True)
    b = _batch()
    _, metrics, td_abs = jax.jit(step)(state, b)
    q = np.asarray(model.apply(state.params, b.state0))
    q_sel = q[np.arange(16), b.action]
    qn = np.asarray(model.apply(state.params, b.state1))
    # at t0 online == target so double-dqn bootstrap = q at online argmax
    boot = qn[np.arange(16), qn.argmax(1)]
    target = b.reward + b.gamma_n * boot * (1 - b.terminal1)
    np.testing.assert_allclose(np.asarray(td_abs), np.abs(q_sel - target),
                               rtol=1e-4, atol=1e-5)


def test_per_weights_scale_loss():
    model, state, step = _dqn_setup()
    b1 = _batch()
    b2 = b1._replace(weight=np.full(16, 0.5, np.float32))
    _, m1, _ = jax.jit(step)(state, b1)
    _, m2, _ = jax.jit(step)(state, b2)
    np.testing.assert_allclose(float(m2["learner/critic_loss"]),
                               0.5 * float(m1["learner/critic_loss"]),
                               rtol=1e-5)


def test_dqn_hard_target_update_period():
    model, state, step = _dqn_setup(target_model_update=3)
    jstep = jax.jit(step)
    b = _batch()
    leaves0 = jax.tree_util.tree_leaves(state.target_params)[0].copy()
    for i in range(1, 4):
        state, _, _ = jstep(state, b)
        t_leaf = jax.tree_util.tree_leaves(state.target_params)[0]
        p_leaf = jax.tree_util.tree_leaves(state.params)[0]
        if i < 3:
            np.testing.assert_array_equal(np.asarray(t_leaf), np.asarray(leaves0))
        else:
            np.testing.assert_array_equal(np.asarray(t_leaf), np.asarray(p_leaf))


def test_dqn_fits_fixed_targets():
    # supervised sanity: repeated steps on one batch drive TD error down
    model, state, step = _dqn_setup(lr=3e-3)
    jstep = jax.jit(step)
    b = _batch()
    losses = []
    for _ in range(300):
        state, metrics, _ = jstep(state, b)
        losses.append(float(metrics["learner/critic_loss"]))
    assert losses[-1] < 0.05 * losses[0]


def _ddpg_setup(coupled=False, obs_dim=3, act_dim=1):
    model = DdpgMlpModel(action_dim=act_dim, actor_hidden=(32, 32),
                         critic_hidden=(32, 32))
    full = model.init(jax.random.PRNGKey(0), jnp.zeros((1, obs_dim)))
    actor_apply = lambda p, o: model.apply(p, o, method=model.forward_actor)
    critic_apply = lambda p, o, a: model.apply(p, o, a,
                                               method=model.forward_critic)
    if coupled:
        tx = make_optimizer(1e-3, clip_grad=40.0)
        state = init_train_state(full, tx)
        step = build_ddpg_train_step_coupled(actor_apply, critic_apply, tx)
    else:
        atx = make_optimizer(1e-4, clip_grad=40.0)
        ctx_ = make_optimizer(1e-3, clip_grad=40.0)
        state = init_ddpg_train_state(full, atx, ctx_)
        step = build_ddpg_train_step(actor_apply, critic_apply, atx, ctx_)
    return model, state, step


def _cont_batch(B=16, obs_dim=3, act_dim=1, seed=0):
    rng = np.random.default_rng(seed)
    return Batch(
        state0=rng.normal(size=(B, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, size=(B, act_dim)).astype(np.float32),
        reward=rng.normal(size=B).astype(np.float32),
        gamma_n=np.full(B, 0.95, np.float32),
        state1=rng.normal(size=(B, obs_dim)).astype(np.float32),
        terminal1=np.zeros(B, np.float32),
        weight=np.ones(B, np.float32),
        index=np.arange(B, dtype=np.int32),
    )


def test_ddpg_split_merge_roundtrip():
    model = DdpgMlpModel(action_dim=1)
    full = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 3)))
    split = split_ddpg_params(full)
    merged = merge_ddpg_params(split["actor"], split["critic"])
    assert jax.tree_util.tree_structure(full) == \
        jax.tree_util.tree_structure(merged)


def test_ddpg_decoupled_step_runs_and_soft_updates():
    model, state, step = _ddpg_setup()
    b = _cont_batch()
    new_state, metrics, td = jax.jit(step)(state, b)
    assert "learner/actor_loss" in metrics
    # soft update with tau=1e-3: target moved slightly toward new params
    t0 = jax.tree_util.tree_leaves(state.target_params)[0]
    t1 = jax.tree_util.tree_leaves(new_state.target_params)[0]
    p1 = jax.tree_util.tree_leaves(new_state.params)[0]
    assert not np.allclose(t0, t1)
    np.testing.assert_allclose(
        np.asarray(t1), np.asarray(0.999 * t0 + 0.001 * p1), rtol=1e-5)


def test_ddpg_coupled_policy_grads_hit_critic():
    # decoupled: critic params after the critic step depend only on the
    # critic loss; coupled: the policy loss also deposits gradients into the
    # critic (reference behaviour) -> different critic update for the same
    # batch and same init.
    _, d_state, d_step = _ddpg_setup(coupled=False)
    _, c_state, c_step = _ddpg_setup(coupled=True)
    b = _cont_batch()
    d_new, _, _ = jax.jit(d_step)(d_state, b)
    c_new, _, _ = jax.jit(c_step)(c_state, b)
    d_critic = d_new.params["critic"]["params"]["critic_out"]["kernel"]
    c_critic = c_new.params["params"]["critic_out"]["kernel"]
    assert not np.allclose(np.asarray(d_critic), np.asarray(c_critic))


def test_ddpg_critic_fits_targets():
    model, state, step = _ddpg_setup()
    jstep = jax.jit(step)
    b = _cont_batch()
    losses = []
    for _ in range(400):
        state, metrics, _ = jstep(state, b)
        losses.append(float(metrics["learner/critic_loss"]))
    assert losses[-1] < 0.1 * losses[0]


def test_sharded_learner_matches_single_device():
    mesh = make_mesh()
    assert mesh.shape["dp"] == 8
    model, state, step = _dqn_setup()
    b = _batch(B=32)
    single = ShardedLearner(step, mesh=None, donate=False)
    sharded = ShardedLearner(step, mesh=mesh, donate=False)
    s1, m1, td1 = single.step(state, b)
    s2, m2, td2 = sharded.step(sharded.place(state), b)
    np.testing.assert_allclose(float(m1["learner/critic_loss"]),
                               float(m2["learner/critic_loss"]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(td1), np.asarray(td2),
                               rtol=1e-4, atol=1e-5)
    # params identical after the step (grad all-reduce == full-batch grad)
    for a, c in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-6)


def test_sharded_learner_batch_really_sharded():
    mesh = make_mesh()
    model, state, step = _dqn_setup()
    sharded = ShardedLearner(step, mesh=mesh, donate=False)
    b = sharded.shard_batch(_batch(B=32))
    devs = {s.device for s in b.state0.addressable_shards}
    assert len(devs) == 8


def test_donation_safe_with_init_train_state():
    # aliased params/target broke donation (donate same buffer twice);
    # init_train_state must keep the sharded+donated step runnable twice
    mesh = make_mesh()
    model, state, step = _dqn_setup()
    learner = ShardedLearner(step, mesh=mesh, donate=True)
    state = learner.place(state)
    b = _batch(B=32)
    state, _, _ = learner.step(state, b)
    state, _, _ = learner.step(state, b)
    assert int(state.step) == 2
    host = learner.host_params(state)
    assert isinstance(jax.tree_util.tree_leaves(host)[0], np.ndarray)
