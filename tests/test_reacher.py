"""ReacherEnv: the multi-dim continuous-action env for the DDPG family
(the reference's DDPG is scalar-action only, reference
core/models/ddpg_mlp_model.py:74-78)."""

from __future__ import annotations

import numpy as np

from pytorch_distributed_tpu.config import EnvParams, build_options
from pytorch_distributed_tpu.envs.classic import ReacherEnv


def params(**kw) -> EnvParams:
    base = dict(env_type="classic", game="reacher", seed=3, state_cha=1,
                state_hei=1, state_wid=10, early_stop=0)
    base.update(kw)
    return EnvParams(**base)


def test_spaces_and_obs():
    env = ReacherEnv(params(), 0)
    assert env.state_shape == (10,)
    assert env.action_space.dim == 2
    obs = env.reset()
    assert obs.shape == (10,) and obs.dtype == np.float32
    # cos/sin entries are bounded
    assert np.all(np.abs(obs[:4]) <= 1.0 + 1e-6)


def test_determinism_and_episode_shape():
    a, b = ReacherEnv(params(), 0), ReacherEnv(params(), 0)
    c = ReacherEnv(params(), 1)
    oa, ob, oc = a.reset(), b.reset(), c.reset()
    np.testing.assert_array_equal(oa, ob)
    assert not np.array_equal(oa, oc)
    total, steps = 0.0, 0
    term = False
    while not term:
        obs, r, term, info = a.step(np.zeros(2, dtype=np.float32))
        assert r <= 0.0  # reward is a negative cost
        total += r
        steps += 1
    assert steps == 150
    assert "solved" in info


def test_torque_moves_fingertip_toward_lower_cost():
    """A crude P-controller on the fingertip delta beats zero torque —
    the 2-dim action channel is live and correctly signed."""

    def rollout(policy, seed=5):
        env = ReacherEnv(params(seed=seed), 0)
        env.reset()
        total = 0.0
        for _ in range(150):
            obs, r, term, _ = env.step(policy(env))
            total += r
        return total

    def pd_policy(env):
        # torque fighting the fingertip error through both joints
        delta = env._fingertip() - env.target
        j1 = np.array([-np.sin(env.q[0]) * env.L1
                       - np.sin(env.q[0] + env.q[1]) * env.L2,
                       np.cos(env.q[0]) * env.L1
                       + np.cos(env.q[0] + env.q[1]) * env.L2])
        j2 = np.array([-np.sin(env.q[0] + env.q[1]) * env.L2,
                       np.cos(env.q[0] + env.q[1]) * env.L2])
        grad = np.array([j1 @ delta, j2 @ delta])
        u = -4.0 * grad - 0.3 * env.qdot
        return np.clip(u, -1, 1).astype(np.float32)

    zero = rollout(lambda env: np.zeros(2, dtype=np.float32))
    pd = rollout(pd_policy)
    assert pd > zero + 1.0, (pd, zero)


def test_config_row_probes_correctly():
    from pytorch_distributed_tpu.factory import probe_env

    opt = build_options(config=16)
    spec = probe_env(opt)
    assert not spec.discrete
    assert spec.action_dim == 2
    assert spec.state_shape == (10,)
