"""Tensor-parallel (mp axis) tests: the Megatron-split DTQN FFN must
produce the same training step as the replicated model, while actually
sharding its kernels over mp (parallel/tensor_parallel.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.memory.sequence_replay import SegmentBatch
from pytorch_distributed_tpu.models.dtqn import DtqnMlpModel
from pytorch_distributed_tpu.ops.losses import (
    init_train_state, make_optimizer,
)
from pytorch_distributed_tpu.ops.sequence_losses import build_dtqn_train_step
from pytorch_distributed_tpu.parallel.learner import ShardedLearner
from pytorch_distributed_tpu.parallel.mesh import make_mesh
from pytorch_distributed_tpu.parallel.tensor_parallel import (
    dtqn_state_shardings,
)


def _setup(T=8, B=4, obs_dim=6, actions=4):
    model = DtqnMlpModel(action_space=actions, state_shape=(obs_dim,),
                         window=T, dim=32, heads=4, depth=2, norm_val=1.0)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, obs_dim)))
    tx = make_optimizer(lr=1e-3)
    state = init_train_state(params, tx)
    step = build_dtqn_train_step(
        lambda p, obs: model.apply(p, obs, method=model.window_q),
        tx, burn_in=0, nstep=3, gamma=0.99, enable_double=True,
        target_model_update=100)
    L = T - 1
    rng = np.random.default_rng(7)
    batch = SegmentBatch(
        obs=rng.normal(size=(B, T, obs_dim)).astype(np.float32),
        action=rng.integers(0, actions, size=(B, L)).astype(np.int32),
        reward=rng.normal(size=(B, L)).astype(np.float32),
        terminal=np.zeros((B, L), dtype=np.float32),
        mask=np.ones((B, L), dtype=np.float32),
        c0=np.zeros((B, 1), dtype=np.float32),
        h0=np.zeros((B, 1), dtype=np.float32),
        weight=np.ones(B, dtype=np.float32),
        index=np.arange(B, dtype=np.int32),
    )
    return state, step, batch


def test_ffn_kernels_shard_over_mp():
    mesh = make_mesh(dp_size=2, mp_size=4)
    state, _, _ = _setup()
    sh = dtqn_state_shardings(state, mesh)
    flat = jax.tree_util.tree_flatten_with_path(sh)[0]
    expand = [s for path, s in flat
              if "Dense_2" in str(path) and "kernel" in str(path)
              and "_Block_" in str(path)]
    contract = [s for path, s in flat
                if "Dense_3" in str(path) and "kernel" in str(path)
                and "_Block_" in str(path)]
    # depth=2 blocks x 3 trees (params, target, adam mu/nu add more)
    assert len(expand) >= 2 and len(contract) >= 2
    for s in expand:
        assert s.spec == jax.sharding.PartitionSpec(None, "mp"), s.spec
    for s in contract:
        assert s.spec == jax.sharding.PartitionSpec("mp", None), s.spec
    # everything attention-side stays replicated
    qkv = [s for path, s in flat
           if "Dense_0" in str(path) and "_Block_" in str(path)]
    assert qkv and all(s.spec == jax.sharding.PartitionSpec() for s in qkv)


def test_mp_sharded_step_matches_replicated():
    """One full train step (fwd+bwd+Adam+target) on a dp2 x mp4 mesh:
    tensor-sharded FFN == replicated math, and the placed kernels really
    live sharded over mp."""
    mesh = make_mesh(dp_size=2, mp_size=4)
    state, step, batch = _setup()

    ref = ShardedLearner(step, mesh, donate=False)
    s0 = ref.place(state)
    s0, m0, td0 = ref.step(s0, batch)

    sh = dtqn_state_shardings(state, mesh)
    tp = ShardedLearner(step, mesh, donate=False, state_shardings=sh)
    s1 = tp.place(state)
    # the expand kernel must actually be split over mp after placement
    block_kernels = [
        (path, leaf) for path, leaf
        in jax.tree_util.tree_flatten_with_path(s1.params)[0]
        if "_Block_0" in str(path) and "Dense_2" in str(path)
        and "kernel" in str(path)]
    assert block_kernels
    for _, leaf in block_kernels:
        assert leaf.sharding.spec == jax.sharding.PartitionSpec(None, "mp")
    s1, m1, td1 = tp.step(s1, batch)

    np.testing.assert_allclose(
        float(m1["learner/critic_loss"]), float(m0["learner/critic_loss"]),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(td1), np.asarray(td0),
                               rtol=1e-4, atol=1e-5)
    p0 = jax.device_get(s0.params)
    p1 = jax.device_get(s1.params)
    flat0 = jax.tree_util.tree_leaves(p0)
    flat1 = jax.tree_util.tree_leaves(p1)
    for a, b in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_mp_requires_dtqn_model():
    """The learner wiring refuses mp>1 on families with no tensor-sharded
    layer, instead of silently training a decorative axis."""
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(1, dp_size=2, mp_size=4)
    assert opt.parallel_params.mp_size == 4
    # the assertion lives in run_learner; exercise the guard directly
    assert "dtqn" not in opt.model_type
