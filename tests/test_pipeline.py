"""Pipeline-parallel (pp axis) tests: the staged DTQN must reproduce its
own sequential math exactly under the GPipe microbatch schedule, shard
its layer axis over pp, and plug into the r2d2 learner contract
(models/dtqn_pipeline.py, parallel/pipeline.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.memory.sequence_replay import SegmentBatch
from pytorch_distributed_tpu.models.dtqn_pipeline import DtqnPipelineModel
from pytorch_distributed_tpu.ops.losses import (
    init_train_state, make_optimizer,
)
from pytorch_distributed_tpu.ops.sequence_losses import build_dtqn_train_step
from pytorch_distributed_tpu.parallel.learner import ShardedLearner
from pytorch_distributed_tpu.parallel.mesh import make_mesh
from pytorch_distributed_tpu.parallel.pipeline import (
    pipeline_state_shardings, pipelined_window_apply,
)


def _model_and_params(T=8, obs_dim=6, actions=4, depth=4, randomize_head=True):
    model = DtqnPipelineModel(action_space=actions, state_shape=(obs_dim,),
                              window=T, dim=32, heads=4, depth=depth,
                              norm_val=1.0)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, obs_dim)))
    if randomize_head:
        # the production head is zero-init (Q starts at 0); an
        # all-zero output would make equivalence tests vacuous
        params["params"]["head_q"]["kernel"] = 0.1 * jax.random.normal(
            jax.random.PRNGKey(1),
            params["params"]["head_q"]["kernel"].shape)
    return model, params


def _segments(T=8, B=8, obs_dim=6, actions=4, seed=7):
    L = T - 1
    rng = np.random.default_rng(seed)
    return SegmentBatch(
        obs=rng.normal(size=(B, T, obs_dim)).astype(np.float32),
        action=rng.integers(0, actions, size=(B, L)).astype(np.int32),
        reward=rng.normal(size=(B, L)).astype(np.float32),
        terminal=np.zeros((B, L), dtype=np.float32),
        mask=np.ones((B, L), dtype=np.float32),
        c0=np.zeros((B, 1), dtype=np.float32),
        h0=np.zeros((B, 1), dtype=np.float32),
        weight=np.ones(B, dtype=np.float32),
        index=np.arange(B, dtype=np.int32),
    )


def test_pipelined_forward_matches_sequential():
    model, params = _model_and_params()
    obs = jnp.asarray(np.random.default_rng(0).normal(
        size=(8, 8, 6)).astype(np.float32))
    q_seq = model.apply(params, obs, method=model.window_q)
    assert float(jnp.sum(jnp.abs(q_seq))) > 1.0  # non-vacuous
    mesh = make_mesh(dp_size=2, pp_size=4)
    for M in (1, 2, 4):
        q_pipe = jax.jit(pipelined_window_apply(model, mesh, M))(params,
                                                                 obs)
        np.testing.assert_allclose(np.asarray(q_pipe), np.asarray(q_seq),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"microbatches={M}")


def test_pipelined_grads_match_sequential():
    """The backward pipeline (grad through scan+ppermute+psum) produces
    the same gradients as the plain scan-over-layers path — including on
    the pp-sharded stacked block params."""
    model, params = _model_and_params()
    obs = jnp.asarray(np.random.default_rng(1).normal(
        size=(8, 8, 6)).astype(np.float32))
    mesh = make_mesh(dp_size=2, pp_size=4)
    papply = pipelined_window_apply(model, mesh, 2)

    loss_seq = lambda p: jnp.sum(jnp.square(
        model.apply(p, obs, method=model.window_q)))
    loss_pipe = lambda p: jnp.sum(jnp.square(papply(p, obs)))
    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g_seq)[0],
            jax.tree_util.tree_flatten_with_path(g_pipe)[0]):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-3, atol=1e-4,
                                   err_msg=str(pa))


def test_block_params_shard_over_pp():
    mesh = make_mesh(dp_size=2, pp_size=4)
    model, params = _model_and_params()
    tx = make_optimizer(lr=1e-3)
    state = init_train_state(params, tx)
    sh = pipeline_state_shardings(state, mesh)
    flat = jax.tree_util.tree_flatten_with_path(sh)[0]
    blocks = [(p, s) for p, s in flat if "blocks" in str(p)]
    assert len(blocks) >= 12 * 3  # 12 leaves x params/target/moments
    for p, s in blocks:
        assert s.spec[0] == "pp", (p, s.spec)
    others = [s for p, s in flat
              if "blocks" not in str(p) and hasattr(s, "spec")]
    assert others and all(
        s.spec == jax.sharding.PartitionSpec() for s in others)


def test_pp_sharded_step_matches_replicated():
    """One full train step (fwd+bwd+Adam+target) on a dp2 x pp4 mesh:
    the staged pipeline == the replicated scan-over-layers math, and the
    placed block params really live split over pp."""
    mesh = make_mesh(dp_size=2, pp_size=4)
    model, params = _model_and_params()
    tx = make_optimizer(lr=1e-3)
    state = init_train_state(params, tx)
    seq_apply = lambda p, obs: model.apply(p, obs, method=model.window_q)
    kw = dict(burn_in=0, nstep=3, gamma=0.99, enable_double=True,
              target_model_update=100)
    step_seq = build_dtqn_train_step(seq_apply, tx, **kw)
    step_pipe = build_dtqn_train_step(
        pipelined_window_apply(model, mesh, 2), tx, **kw)
    batch = _segments()

    ref = ShardedLearner(step_seq, mesh, donate=False)
    s0 = ref.place(state)
    s0, m0, td0 = ref.step(s0, batch)

    sh = pipeline_state_shardings(state, mesh)
    pl = ShardedLearner(step_pipe, mesh, donate=False, state_shardings=sh)
    s1 = pl.place(state)
    kernels = [
        (path, leaf) for path, leaf
        in jax.tree_util.tree_flatten_with_path(s1.params)[0]
        if "blocks" in str(path) and "qkv_k" in str(path)]
    assert kernels
    for _, leaf in kernels:
        assert leaf.sharding.spec[0] == "pp"
    s1, m1, td1 = pl.step(s1, batch)

    np.testing.assert_allclose(
        float(m1["learner/critic_loss"]), float(m0["learner/critic_loss"]),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(td1), np.asarray(td0),
                               rtol=1e-3, atol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s0.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s1.params))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-3, atol=1e-4)


def test_acting_path_matches_window_q_tail():
    """The staged model honours the DTQN acting contract (inherited
    leading-aligned window carry)."""
    model, params = _model_and_params()
    obs = jnp.asarray(np.random.default_rng(2).normal(
        size=(2, 8, 6)).astype(np.float32))
    carry = model.zero_carry(2)
    apply = jax.jit(lambda p, o, c: model.apply(p, o, c))
    for t in range(4):
        q_act, carry = apply(params, obs[:, t], carry)
    q_win = model.apply(params, obs[:, :4], method=model.window_q)
    np.testing.assert_allclose(np.asarray(q_act), np.asarray(q_win[:, 3]),
                               rtol=1e-4, atol=1e-5)


def test_factory_builds_pipe_row_and_step_runs():
    """CONFIGS row 18 constructs end-to-end and one update runs; with
    pp_size>1 the factory swaps in the pipelined window apply."""
    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.factory import (
        build_model, build_train_state_and_step, init_params, probe_env,
    )

    opt = build_options(18, seq_len=7, burn_in=0, tf_depth=4,
                        pp_size=4, pp_microbatches=2, dp_size=2)
    assert opt.model_type == "dtqn-pipe"
    spec = probe_env(opt)
    model = build_model(opt, spec)
    assert isinstance(model, DtqnPipelineModel)
    params = init_params(opt, spec, model, seed=0)
    mesh = make_mesh(dp_size=2, pp_size=4)
    state, step = build_train_state_and_step(opt, spec, model, params,
                                             mesh=mesh)
    sh = pipeline_state_shardings(state, mesh)
    learner = ShardedLearner(step, mesh, donate=False, state_shardings=sh)
    s = learner.place(state)
    batch = _segments(T=8, B=8, obs_dim=spec.state_shape[0],
                      actions=spec.num_actions)
    s, metrics, pr = learner.step(s, batch)
    assert int(jax.device_get(s.step)) == 1
    assert np.isfinite(float(metrics["learner/critic_loss"]))
    assert pr.shape == (8,)
