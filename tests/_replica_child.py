"""Spawn child for the slow replica-topology drill
(tests/test_replicas.py): one REAL replica learner process — jax
grad/apply split, lease client, checkpoint-epoch rejoin — dialling the
parent's gateway over loopback.  ``REPLICA_FAULTS=kill@N`` SIGKILLs it
at round N through the production fault plane (utils/faults.py); a
SIGTERM from the parent is the preemption notice (drain + exit 0)."""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--replica-id", type=int, required=True)
    ap.add_argument("--root-dir", required=True)
    ap.add_argument("--refs", default="replicadrill")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--steps", type=int, default=500000)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_tpu.config import build_options
    from pytorch_distributed_tpu.fleet import run_replica_host

    opt = build_options(
        1, root_dir=args.root_dir, refs=args.refs, seed=args.seed,
        hidden_dim=32, batch_size=8, memory_size=128, learn_start=32,
        steps=args.steps, replicas=2,
        join_timeout_s=120.0, evaluator_nepisodes=0,
    )
    # lease_s lives on both the replica and gateway planes (ISSUE 16),
    # so the bare build_options override is ambiguous — set it directly
    opt.replica_params.lease_s = 1.5
    run_replica_host(opt, args.coordinator, args.replica_id)


if __name__ == "__main__":
    main()
