"""Pallas hierarchical PER sampler: interpret-mode equivalence against the
flat XLA scheme, distribution correctness, and the device_per plug-in hook.
On CPU the kernel runs in interpret mode; the real-TPU path compiles the
same kernel (validated on hardware; see ops/pallas_sampling.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.pallas_sampling import (
    flat_sample, hierarchical_sample,
)


def _priorities(n: int, zero_frac: float = 0.3, seed: int = 0):
    rng = np.random.default_rng(seed)
    return np.where(rng.random(n) < zero_frac, 0.0,
                    rng.random(n)).astype(np.float32)


class TestHierarchicalSample:
    @pytest.mark.parametrize("n", [1000, 4096, 131072])
    def test_matches_flat_scheme_exactly(self, n):
        prio = jnp.asarray(_priorities(n))
        key = jax.random.PRNGKey(7)
        idx_h, p_h = hierarchical_sample(prio, key, 64, interpret=True)
        idx_f, p_f = flat_sample(prio, key, 64)
        np.testing.assert_array_equal(np.asarray(idx_h), np.asarray(idx_f))
        np.testing.assert_allclose(np.asarray(p_h), np.asarray(p_f),
                                   rtol=1e-6)

    def test_never_draws_empty_rows(self):
        # half-filled ring: tail rows hold priority 0
        prio = np.zeros(8192, np.float32)
        prio[:3000] = _priorities(3000, zero_frac=0.0)
        idx, _ = hierarchical_sample(jnp.asarray(prio),
                                     jax.random.PRNGKey(3), 256,
                                     interpret=True)
        assert (np.asarray(idx) < 3000).all()

    def test_distribution_proportional_to_priority(self):
        # coarse chi-square-ish check on a small support
        prio = np.zeros(2048, np.float32)
        hot = [5, 100, 1024, 2000]
        weights = [1.0, 2.0, 4.0, 8.0]
        for i, w in zip(hot, weights):
            prio[i] = w
        counts = np.zeros(2048)
        for s in range(40):
            idx, _ = hierarchical_sample(
                jnp.asarray(prio), jax.random.PRNGKey(s), 128,
                interpret=True)
            np.add.at(counts, np.asarray(idx), 1)
        frac = counts[hot] / counts.sum()
        expect = np.asarray(weights) / np.sum(weights)
        np.testing.assert_allclose(frac, expect, atol=0.03)

    def test_single_block_edge(self):
        # N smaller than one superblock exercises the padding path
        prio = jnp.asarray(_priorities(100, zero_frac=0.0))
        idx, _ = hierarchical_sample(prio, jax.random.PRNGKey(1), 32,
                                     interpret=True)
        assert (np.asarray(idx) < 100).all()


class TestDevicePerHook:
    def test_per_sample_accepts_custom_draw(self):
        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay, per_sample,
        )
        from pytorch_distributed_tpu.utils.experience import Transition

        replay = DevicePerReplay(capacity=512, state_shape=(4,),
                                 state_dtype=np.float32)
        n = 64
        rng = np.random.default_rng(0)
        replay.feed_chunk(Transition(
            state0=rng.normal(size=(n, 4)).astype(np.float32),
            action=np.arange(n, dtype=np.int32),
            reward=np.ones(n, np.float32),
            gamma_n=np.full(n, 0.99, np.float32),
            state1=rng.normal(size=(n, 4)).astype(np.float32),
            terminal1=np.zeros(n, np.float32)))

        def draw(p, key, batch_size):
            return hierarchical_sample(p, key, batch_size, interpret=True)

        batch = jax.jit(
            lambda st, k: per_sample(st, k, 32, jnp.float32(0.4),
                                     sample_fn=draw)
        )(replay.state, jax.random.PRNGKey(0))
        idx = np.asarray(batch.index)
        assert (idx < n).all()  # only fed rows are drawable
        assert np.isfinite(np.asarray(batch.weight)).all()
        assert (np.asarray(batch.weight) <= 1.0 + 1e-6).all()
