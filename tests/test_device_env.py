"""Device env fleet (ISSUE 7): parity oracle drill + fused rollout.

The parity chain has three legs, each bit-exact:

1. **f64 numpy kernel == the real host ``PongSimEnv``** over full
   episodes (auto-reset, truncation, ``final_obs``) with the host
   class's RNG replaced by the device env's counter stream
   (``CounterRng``) — proves the PORT is op-for-op faithful to the
   production host env, including the preprocessing pipeline.
2. **jitted f32 device env == f32 numpy kernel** over full episodes —
   proves XLA executes the same arithmetic the oracle runs (no fusion
   / FMA / layout surprises), auto-resets included.
3. **f32 device env == the real f64 ``PongSimEnv``** from an identical
   mid-court state over a horizon with binary-representable velocities
   — a direct device-vs-host bridge with no RNG and no dtype drift
   (the technique tests/test_native_pong.py uses for the C++ stepper).

The fused rollout engine is pinned against the HOST reference loop:
``build_packed_act`` + ``NStepAssembler`` over ``DevicePongVectorEnv``
must produce the identical transition stream (states, rewards,
gamma_n, terminals) the one-dispatch scan emits.
"""

from __future__ import annotations

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.envs.device_env import (
    CounterRng, DevicePongVectorEnv, build_device_env,
    device_env_supported, make_device_pong,
)
from pytorch_distributed_tpu.envs.pong_sim import PongSimEnv


def _env_params(**kw):
    opt = build_options(4)
    for k, v in kw.items():
        setattr(opt.env_params, k, v)
    return opt.env_params


def _patched_hosts(ep, slots):
    """Real PongSimEnv instances replaying the device counter stream.
    The shim is installed post-__init__ (the constructor's throwaway
    ``_reset_ball`` draws are not part of the device stream), so the
    first ``reset()`` consumes counters 1..3 exactly like the device
    ``init``."""
    hosts = []
    for s in slots:
        e = PongSimEnv(ep, process_ind=s - ep.seed)
        e.rng = CounterRng(s)
        hosts.append(e)
    return hosts


class TestParityOracle:
    def test_f64_oracle_matches_host_pongsim_full_episodes(self):
        """Leg 1: numpy f64 kernel == the real host class, through
        auto-reset boundaries (early_stop=40 forces several)."""
        ep = _env_params(early_stop=40)
        slots = [ep.seed + j for j in range(3)]
        oracle = make_device_pong(ep, slots, xp=np, dtype=np.float64)
        st = oracle.init()
        hosts = _patched_hosts(ep, slots)
        obs_h = np.stack([e.reset() for e in hosts])
        np.testing.assert_array_equal(np.asarray(st.stack), obs_h)
        rng = np.random.default_rng(0)
        resets = 0
        for _t in range(100):
            acts = rng.integers(0, 6, size=3)
            st, out = oracle.step(st, acts)
            for j, e in enumerate(hosts):
                o, r, term, info = e.step(int(acts[j]))
                assert float(out.reward[j]) == r
                assert bool(out.terminal[j]) == bool(term)
                assert bool(out.truncated[j]) == bool(
                    info.get("truncated", False))
                if term:
                    resets += 1
                    # true terminal obs preserved, then auto-reset
                    np.testing.assert_array_equal(
                        np.asarray(out.final_obs[j]), o)
                    o = e.reset()
                np.testing.assert_array_equal(np.asarray(out.obs[j]), o)
        assert resets >= 3, "horizon must cross episode boundaries"

    def test_device_f32_matches_numpy_oracle_full_episodes(self):
        """Leg 2: jitted XLA f32 == numpy f32, every StepOut field."""
        import jax

        ep = _env_params(early_stop=30)
        dev = build_device_env(ep, 0, 4)
        orc = make_device_pong(ep, [ep.seed + j for j in range(4)],
                               xp=np, dtype=np.float32)
        jstep = jax.jit(dev.step)
        sd, so = dev.init(), orc.init()
        for fd, fo in zip(sd, so):
            np.testing.assert_array_equal(np.asarray(fd), fo)
        rng = np.random.default_rng(1)
        for t in range(80):
            acts = rng.integers(0, 6, size=4).astype(np.int32)
            sd, od = jstep(sd, acts)
            so, oo = orc.step(so, acts)
            for name, a, b in zip(od._fields, od, oo):
                assert np.array_equal(np.asarray(a), b), (t, name)

    def test_device_f32_matches_real_pongsim_representable_horizon(self):
        """Leg 3: device vs the UNMODIFIED f64 host env from one
        mid-court state.  Velocities are binary fractions (1.5, 0.25)
        and the enemy paddle starts locked onto the ball, so every
        f32 and f64 trajectory value is exact until the first paddle
        contact — frames must match bit-for-bit."""
        import jax

        ep = _env_params()
        host = PongSimEnv(ep, process_ind=0)
        host.reset()
        host.player_y, host.enemy_y = 20.0, 40.0
        host.ball_x, host.ball_y = 42.0, 40.0
        host.ball_vx, host.ball_vy = 1.5, 0.25
        host._score = [0, 0]

        dev = build_device_env(ep, 0, 1)
        st = dev.init()
        st = st._replace(
            player_y=np.asarray([20.0], np.float32),
            enemy_y=np.asarray([40.0], np.float32),
            ball_x=np.asarray([42.0], np.float32),
            ball_y=np.asarray([40.0], np.float32),
            ball_vx=np.asarray([1.5], np.float32),
            ball_vy=np.asarray([0.25], np.float32))
        jstep = jax.jit(dev.step)
        for t, a in enumerate([0, 2, 3, 0, 1]):
            obs_h, r_h, term_h, _ = host.step(a)
            st, out = jstep(st, np.asarray([a], np.int32))
            assert r_h == 0.0 and float(out.reward[0]) == 0.0
            assert not term_h and not bool(out.terminal[0])
            np.testing.assert_array_equal(np.asarray(out.obs[0, -1]),
                                          obs_h[-1])

    def test_game_over_scores_resets_and_reports(self):
        """Scoring + game end via state surgery: player at match point,
        ball about to cross the enemy goal line — both the oracle and
        the device must score, flag the terminal, report (0, 21), and
        auto-reset with the true final stack in final_obs."""
        import jax

        ep = _env_params()
        dev = build_device_env(ep, 0, 2)
        orc = make_device_pong(ep, [ep.seed, ep.seed + 1], xp=np,
                               dtype=np.float32)
        sd, so = dev.init(), orc.init()

        def surgery(s):
            return s._replace(
                score_player=np.asarray([20, 0], np.int32),
                ball_x=np.asarray([2.0, 42.0], np.float32),
                ball_y=np.asarray([70.0, 40.0], np.float32),
                ball_vx=np.asarray([-1.4, 1.4], np.float32),
                ball_vy=np.asarray([0.0, 0.0], np.float32),
                enemy_y=np.asarray([10.0, 40.0], np.float32))

        sd, so = surgery(sd), surgery(so)
        sd, od = jax.jit(dev.step)(sd, np.zeros(2, np.int32))
        so, oo = orc.step(so, np.zeros(2, np.int32))
        for name, a, b in zip(od._fields, od, oo):
            assert np.array_equal(np.asarray(a), b), name
        assert float(od.reward[0]) == 1.0 and float(od.reward[1]) == 0.0
        assert bool(od.terminal[0]) and not bool(od.terminal[1])
        assert not bool(od.truncated[0])
        assert tuple(np.asarray(od.score[0])) == (0, 21)
        # env 0 auto-reset: returned obs is a fresh stack (all frames
        # equal), final_obs keeps the terminal stack
        obs0 = np.asarray(od.obs[0])
        for k in range(1, obs0.shape[0]):
            np.testing.assert_array_equal(obs0[0], obs0[k])
        assert not np.array_equal(np.asarray(od.final_obs[0]), obs0)
        # scores reset on device state too
        assert int(np.asarray(sd.score_player)[0]) == 0

    def test_wrapper_vector_env_contract(self):
        """DevicePongVectorEnv mirrors envs/vector.py: shapes, spaces,
        final_obs/truncated infos, auto-reset."""
        ep = _env_params(early_stop=5)
        env = DevicePongVectorEnv(ep, process_ind=0, num_envs=3)
        obs = env.reset()
        assert obs.shape == (3, 4, 84, 84) and obs.dtype == np.uint8
        assert env.state_shape == (4, 84, 84)
        assert env.action_space.n == 6 and env.norm_val == 255.0
        for _ in range(5):
            obs, rew, term, infos = env.step(np.zeros(3, np.int64))
        assert term.all()
        for j in range(3):
            assert infos[j].get("truncated") is True
            assert "final_obs" in infos[j]
            assert not np.array_equal(infos[j]["final_obs"], obs[j])
        _, _, term, _ = env.step(np.zeros(3, np.int64))
        assert not term.any()


class TestSlotSeedContract:
    """ISSUE 7 satellite: env j of actor i takes seed slot i*N + j on
    EVERY backend, so backend choice never changes the seed stream."""

    def test_python_backend_slots(self):
        from pytorch_distributed_tpu.factory import build_env_vector

        opt = build_options(4)
        opt.env_params.native_env = False
        v = build_env_vector(opt, process_ind=2, num_envs=3)
        assert [e.seed for e in v.envs] == [
            opt.env_params.seed + 2 * 3 + j for j in range(3)]

    def test_device_backend_slots(self):
        ep = _env_params()
        env = build_device_env(ep, process_ind=2, num_envs=3)
        st = env.init()
        np.testing.assert_array_equal(
            np.asarray(st.seed),
            np.asarray([ep.seed + 2 * 3 + j for j in range(3)],
                       np.uint32))

    def test_slot_identity_across_split_points(self):
        """Slot (i*N + j) identifies the stream, not (i, j): actor 1
        of width 2 must reproduce envs 2..3 of one width-4 actor —
        checked per backend against its own RNG scheme."""
        ep = _env_params()
        a = build_device_env(ep, process_ind=1, num_envs=2)
        b = build_device_env(ep, process_ind=0, num_envs=4)
        oa = np.asarray(a.init().stack)
        ob = np.asarray(b.init().stack)
        np.testing.assert_array_equal(oa, ob[2:4])
        try:
            from pytorch_distributed_tpu.envs.native_pong import (
                NativePongVectorEnv, get_lib,
            )

            get_lib()
        except Exception:  # noqa: BLE001 - no toolchain
            return
        na = NativePongVectorEnv(ep, 1, 2)
        nb = NativePongVectorEnv(ep, 0, 4)
        np.testing.assert_array_equal(na.reset(), nb.reset()[2:4])

    def test_resolve_backend_gates(self):
        import warnings

        from pytorch_distributed_tpu.factory import resolve_actor_backend

        opt = build_options(4, actor_backend="device")
        assert resolve_actor_backend(opt) == "device"
        assert device_env_supported(opt.env_params)
        # an explicit family must name the env_type's OWN device
        # implementation — substituting a different game raises
        opt.env_params.device_env_family = "pong"
        assert device_env_supported(opt.env_params)
        mismatched = build_options(3).env_params  # cartpole row
        mismatched.device_env_family = "pong"
        with pytest.raises(ValueError, match="does not implement"):
            device_env_supported(mismatched)
        # unsupported env family downgrades loudly
        opt2 = build_options(1, actor_backend="device")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert resolve_actor_backend(opt2) == "pipelined"
        assert any("device env" in str(x.message) for x in w)
        # non-dqn family downgrades loudly
        opt3 = build_options(2, actor_backend="device")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert resolve_actor_backend(opt3) == "pipelined"
        assert any("dqn" in str(x.message) for x in w)


# ---------------------------------------------------------------------------
# the fused rollout engine
# ---------------------------------------------------------------------------

def _linear_policy(state_shape, num_actions=6, seed=0):
    import jax.numpy as jnp

    dim = int(np.prod(state_shape))
    w = jnp.asarray(np.random.default_rng(seed).normal(
        size=(dim, num_actions)).astype(np.float32) * 0.05)

    def apply_fn(params, obs):
        x = obs.reshape((obs.shape[0], -1)).astype(jnp.float32) / 255.0
        return x @ params

    return apply_fn, w


class TestFusedRollout:
    N, NSTEP, GAMMA, K, DISPATCHES = 3, 3, 0.99, 5, 5

    @pytest.fixture(scope="class")
    def run(self):
        """One engine run + one host-reference run over the same env,
        policy and key streams; class-scoped so every assertion shares
        the compiles."""
        import jax
        import jax.numpy as jnp

        from pytorch_distributed_tpu.models.policies import (
            apex_epsilons, build_fused_rollout, build_packed_act,
            init_rollout_carry,
        )
        from pytorch_distributed_tpu.ops.nstep import NStepAssembler
        from pytorch_distributed_tpu.utils.rngs import process_key

        ep = _env_params(early_stop=20)
        N, NSTEP, GAMMA, K = self.N, self.NSTEP, self.GAMMA, self.K
        env = build_device_env(ep, 0, N)
        apply_fn, w = _linear_policy(env.state_shape)
        base_key = process_key(100, "actor", 0)
        eps = jnp.asarray(apex_epsilons(0, 2, N, 0.4, 7.0))

        roll = build_fused_rollout(apply_fn, env, nstep=NSTEP,
                                   gamma=GAMMA, rollout_ticks=K,
                                   emit="chunk")
        carry = init_rollout_carry(env, NSTEP)
        chunks = []
        for d in range(self.DISPATCHES):
            carry, chunk = roll(w, carry, base_key, jnp.int32(d * K),
                                eps)
            chunks.append(jax.device_get(chunk._asdict()))

        # host reference: packed act + host assembler over the wrapper
        wrap = DevicePongVectorEnv(ep, 0, N)
        act = build_packed_act(apply_fn)
        asms = [NStepAssembler(NSTEP, GAMMA) for _ in range(N)]
        obs = wrap.reset()
        host = [[] for _ in range(N)]
        qmax_ref = []
        for t in range(self.DISPATCHES * K):
            packed = np.asarray(act(w, obs, base_key, t, eps))
            qmax_ref.append(packed[2].copy())
            actions = packed[0].astype(np.int64)
            nobs, rew, term, infos = wrap.step(actions)
            for j in range(N):
                true_next = infos[j].get("final_obs", nobs[j])
                for tr in asms[j].feed(
                        obs[j], actions[j], float(rew[j]), true_next,
                        bool(term[j]),
                        truncated=bool(infos[j].get("truncated",
                                                    False))):
                    host[j].append(tr)
            obs = nobs
        return dict(chunks=chunks, host=host, qmax_ref=qmax_ref)

    def _fused_rows(self, chunks):
        """Valid emissions in (tick, env) order with their global
        emission tick."""
        rows = []
        for d, ch in enumerate(chunks):
            for k in range(self.K):
                for j in range(self.N):
                    if ch["valid"][k][j]:
                        rows.append((d * self.K + k, j,
                                     {f: np.asarray(ch[f][k][j])
                                      for f in ch}))
        return rows

    def test_warmup_ticks_are_invalid_then_all_valid(self, run):
        ch0 = run["chunks"][0]
        valid = np.asarray(ch0["valid"])
        assert not valid[:self.NSTEP].any()
        assert valid[self.NSTEP:].all()
        for ch in run["chunks"][1:]:
            assert np.asarray(ch["valid"]).all()

    def test_transition_stream_matches_host_assembler(self, run):
        rows = self._fused_rows(run["chunks"])
        per_env = [[] for _ in range(self.N)]
        for _te, j, row in rows:
            per_env[j].append(row)
        compared = 0
        for j in range(self.N):
            m = min(len(run["host"][j]), len(per_env[j]))
            assert m >= 15  # crosses several truncation boundaries
            for i in range(m):
                h, f = run["host"][j][i], per_env[j][i]
                np.testing.assert_array_equal(h.state0, f["state0"])
                np.testing.assert_array_equal(h.state1, f["state1"])
                assert int(h.action) == int(f["action"])
                assert h.reward == f["reward"]
                assert h.gamma_n == f["gamma_n"]
                assert h.terminal1 == f["terminal1"]
                compared += 1
        assert compared >= 45

    def test_bootstrap_q_column_is_the_next_forward(self, run):
        """Steady-state windows close at te-1 and bootstrap from the
        forward at te — the emission tick itself (the host pending
        queue's exact semantics)."""
        checked = 0
        for te, j, row in self._fused_rows(run["chunks"]):
            steady = (row["gamma_n"] == np.float32(
                self.GAMMA ** self.NSTEP)) and row["terminal1"] == 0 \
                and bool(row["prio_ok"])
            if steady:
                assert row["q_boot"] == run["qmax_ref"][te][j]
                checked += 1
        assert checked >= 10

    def test_truncated_windows_marked_no_priority(self, run):
        rows = self._fused_rows(run["chunks"])
        trunc_rows = [r for _, _, r in rows if not r["prio_ok"]]
        # early_stop=20 with 25 ticks -> one boundary, nstep windows
        # per env close there
        assert len(trunc_rows) >= self.N
        for r in trunc_rows:
            assert r["terminal1"] == 0.0  # truncation still bootstraps

    def test_rollout_priorities_formula(self, run):
        from pytorch_distributed_tpu.models.policies import (
            rollout_priorities,
        )

        rows = [r for _, _, r in self._fused_rows(run["chunks"])]
        flat = {f: np.asarray([r[f] for r in rows])
                for f in ("reward", "gamma_n", "terminal1", "q_boot",
                          "q_sel", "prio_ok")}
        pr = rollout_priorities(flat, True)
        assert pr.shape == (len(rows),)
        for i, r in enumerate(rows):
            if not r["prio_ok"]:
                assert pr[i] is None
            else:
                want = abs(float(r["reward"])
                           + float(r["gamma_n"])
                           * (1.0 - float(r["terminal1"]))
                           * float(r["q_boot"]) - float(r["q_sel"]))
                assert pr[i] == pytest.approx(want)
        assert rollout_priorities(flat, False) is None

    def test_replay_emit_matches_chunk_emit(self, run):
        """emit="replay" scatters the SAME rows straight into a device
        ring (zero host round-trip) — contents must equal the chunk
        emissions row for row."""
        import jax
        import jax.numpy as jnp

        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplay,
        )
        from pytorch_distributed_tpu.models.policies import (
            apex_epsilons, build_fused_rollout, init_rollout_carry,
        )
        from pytorch_distributed_tpu.utils.rngs import process_key

        ep = _env_params(early_stop=20)
        env = build_device_env(ep, 0, self.N)
        apply_fn, w = _linear_policy(env.state_shape)
        roll = build_fused_rollout(apply_fn, env, nstep=self.NSTEP,
                                   gamma=self.GAMMA,
                                   rollout_ticks=self.K, emit="replay")
        ring = DeviceReplay(capacity=256, state_shape=env.state_shape,
                            state_dtype=np.uint8)
        carry = init_rollout_carry(env, self.NSTEP)
        rs = ring.state
        base_key = process_key(100, "actor", 0)
        eps = jnp.asarray(apex_epsilons(0, 2, self.N, 0.4, 7.0))
        fed = 0
        for d in range(self.DISPATCHES):
            carry, rs, stats = roll(w, carry, rs, base_key,
                                    jnp.int32(d * self.K), eps)
            fed += int(stats.fed)
        rows = [r for _, _, r in self._fused_rows(run["chunks"])]
        assert fed == len(rows)
        rs_h = jax.device_get(rs)
        assert int(rs_h.fill) == fed
        for i, row in enumerate(rows):
            for f in ("state0", "action", "reward", "gamma_n",
                      "state1", "terminal1"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(rs_h, f)[i]), row[f],
                    err_msg=f"ring row {i} field {f}")


class TestDeviceActorDriver:
    def test_bounded_device_run_feeds_counts_and_exports_perf(self,
                                                              tmp_path,
                                                              monkeypatch):
        """The actor_backend=device driver end to end in-process: real
        dqn-cnn model, device Pong fleet, recording sink.  Checks the
        transition stream arrives, the clock advances K*N per
        dispatch, and the perf plane captured the rollout program
        (frames counter + per-frame FLOPs + retrace registration)."""
        monkeypatch.setenv("TPU_APEX_PERF", "1")
        from pytorch_distributed_tpu.agents.actor import (
            bounded_actor_run,
        )
        from pytorch_distributed_tpu.utils import perf

        perf.reset()
        opt = build_options(
            4, root_dir=str(tmp_path), refs="dev_drv", num_actors=1,
            num_envs_per_actor=4, actor_backend="device",
            visualize=False, actor_freq=10 ** 9,
            actor_sync_freq=10 ** 9)
        opt.env_params.device_rollout_ticks = 2
        dispatches = 4
        res = bounded_actor_run(opt, ticks=dispatches)
        stream = res["stream"]
        # warmup holds back nstep emissions per env
        expected = (dispatches * 2 - opt.agent_params.nstep) * 4
        assert len(stream) == expected
        t0, pr0 = stream[0]
        assert t0.state0.shape == (4, 84, 84)
        assert t0.state0.dtype == np.uint8
        assert pr0 is None  # uniform replay: no actor-side priorities
        h = res["harness"]
        assert h.env is None  # no host env objects in a device actor
        assert h.perf._frames == dispatches * 2 * 4
        assert h.perf.flops_per_frame and h.perf.flops_per_frame > 0
        assert "device_rollout" in h.perf.retraces._fns
        perf.reset()


class TestFleetStatusActorsBlock:
    def test_health_snapshot_reports_per_actor_rate_and_backend(self,
                                                                tmp_path):
        """ISSUE 7 satellite: the gateway STATUS payload carries a
        per-LOCAL-actor block — env frames/s derived from the progress
        board's tick marks over the provider's rate window, plus the
        resolved schedule — and it is what fleet_top's --json prints."""
        import json as _json
        import time as _time

        from pytorch_distributed_tpu.fleet import FleetTopology

        opt = build_options(
            4, num_actors=2, num_envs_per_actor=8, seed=7,
            root_dir=str(tmp_path), actor_backend="device",
            visualize=False)
        topo = FleetTopology(opt, local_actors=2, port=0)
        try:
            h0 = topo._health_snapshot()  # anchors the rate window
            # two dispatches' worth of ticks on actor-0, one on actor-1
            topo.progress_board.note_start("actor-0")
            topo.progress_board.note_start("actor-1")
            topo.progress_board.bump("actor-0", n=4)
            topo.progress_board.bump("actor-1", n=2)
            _time.sleep(0.6)  # provider ignores sub-0.5s windows
            h1 = topo._health_snapshot()
            actors = h1["actors"]
            assert set(actors) == {"0", "1"}
            for slot in ("0", "1"):
                assert actors[slot]["backend"] == "device"
            # rate = marks * num_envs / window; exact dt is wall-clock,
            # so assert proportions and positivity instead
            assert actors["0"]["env_frames_per_sec"] > 0
            assert actors["0"]["env_frames_per_sec"] > \
                actors["1"]["env_frames_per_sec"]
            _json.dumps(h1)  # the --json path must serialize
        finally:
            topo.gateway.close()
