"""Ring attention + DTQN: sequence-parallel attention equals the dense
reference on the 8-virtual-device CPU mesh, the transformer Q-network
plugs into it unchanged, and the DTQN family trains end to end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.ring_attention import (
    full_attention, ring_attention,
)
from pytorch_distributed_tpu.parallel.mesh import make_mesh


def _qkv(B=4, H=2, T=32, D=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=(B, H, T, D))
                             .astype(np.float32)) for _ in range(3))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_full_attention(self, causal):
        mesh = make_mesh(dp_size=2, sp_size=4)
        q, k, v = _qkv()
        out_ring = ring_attention(q, k, v, mesh, causal=causal)
        out_full = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_full),
                                   rtol=1e-4, atol=1e-5)

    def test_sp_only_mesh(self):
        mesh = make_mesh(dp_size=1, sp_size=8)
        q, k, v = _qkv(B=2, T=64)
        out_ring = ring_attention(q, k, v, mesh, causal=True)
        out_full = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_full),
                                   rtol=1e-4, atol=1e-5)

    def test_causality(self):
        # perturbing future tokens must not change past outputs
        mesh = make_mesh(dp_size=1, sp_size=8)
        q, k, v = _qkv(B=2, T=32)
        out1 = np.asarray(ring_attention(q, k, v, mesh, causal=True))
        k2 = k.at[:, :, 24:].set(0.0)
        v2 = v.at[:, :, 24:].set(9.9)
        out2 = np.asarray(ring_attention(q, k2, v2, mesh, causal=True))
        np.testing.assert_allclose(out1[:, :, :24], out2[:, :, :24],
                                   rtol=1e-5)
        assert np.abs(out1[:, :, 24:] - out2[:, :, 24:]).max() > 1e-3


class TestDtqnModel:
    def _model(self, window=9, attn=None):
        from pytorch_distributed_tpu.models.dtqn import DtqnMlpModel

        return DtqnMlpModel(action_space=3, state_shape=(4,),
                            window=window, dim=32, heads=2, depth=1,
                            attn=attn)

    def test_acting_path_matches_window_path(self):
        """Stepping obs one by one through the rolling carry must produce
        the same Q as the learner's one-shot causal window pass."""
        model = self._model()
        obs0 = jnp.zeros((2, 4))
        params = model.init(jax.random.PRNGKey(0), obs0)
        seq = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 4))
        q_win = model.apply(params, seq, method=model.window_q)  # (2,6,3)
        carry = model.zero_carry(2)
        for t in range(6):
            q_t, carry = model.apply(params, seq[:, t], carry)
            np.testing.assert_allclose(np.asarray(q_t),
                                       np.asarray(q_win[:, t]),
                                       rtol=1e-4, atol=1e-5)

    def test_rolling_window_when_full(self):
        """Past the acting context (window - 1: the table's last position
        is bootstrap-only and untrained) the oldest obs falls off; the
        model output equals a window pass over the last act_window
        observations."""
        model = self._model(window=4)
        A = model.act_window  # 3
        obs0 = jnp.zeros((1, 4))
        params = model.init(jax.random.PRNGKey(0), obs0)
        seq = jax.random.normal(jax.random.PRNGKey(2), (1, 10, 4))
        carry = model.zero_carry(1)
        for t in range(10):
            q_t, carry = model.apply(params, seq[:, t], carry)
        q_win = model.apply(params, seq[:, -A:], method=model.window_q)
        np.testing.assert_allclose(np.asarray(q_t),
                                   np.asarray(q_win[:, -1]),
                                   rtol=1e-4, atol=1e-5)

    def test_ring_attention_injection_matches(self):
        from pytorch_distributed_tpu.models.dtqn import with_ring_attention

        mesh = make_mesh(dp_size=2, sp_size=4)
        model = self._model(window=16)
        obs0 = jnp.zeros((2, 4))
        params = model.init(jax.random.PRNGKey(0), obs0)
        seq = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 4))
        q_local = model.apply(params, seq, method=model.window_q)
        rmodel = with_ring_attention(model, mesh)
        q_ring = rmodel.apply(params, seq, method=rmodel.window_q)
        np.testing.assert_allclose(np.asarray(q_ring),
                                   np.asarray(q_local),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
@pytest.mark.timeout(1200)
def test_dtqn_sequence_parallel_learner_runs(tmp_path):
    """The sp>1 path end to end: a dp2 x sp4 mesh, DTQN's attention swapped
    for ring attention inside the jitted train step, short topology run."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    opt = build_options(
        15, root_dir=str(tmp_path), num_actors=1, steps=40, learn_start=4,
        batch_size=8, memory_size=1024, seq_len=15, seq_overlap=7,
        nstep=3, actor_sync_freq=20, param_publish_freq=5, learner_freq=10,
        evaluator_freq=30, early_stop=60, dp_size=2, sp_size=4)
    topo = runtime.train(opt, backend="thread")
    assert topo.clock.learner_step.value >= 40


@pytest.mark.slow
@pytest.mark.timeout(2400)
def test_dtqn_chain_topology_learns(tmp_path):
    """Online DTQN learns the chain MDP end to end.

    The online loop has one known stochastic failure mode (documented at
    models/dtqn.py zero-init head): under unlucky actor/learner thread
    interleaving it can park on the flat overestimation plateau.  That is
    a property of this aggressive 1500-step smoke budget, not of the
    framework, so the bar allows a second seed before failing — both
    misses would mean a real regression."""
    from pytorch_distributed_tpu import runtime
    from pytorch_distributed_tpu.config import build_options

    seeds = (100, 101)
    last = None
    for seed in seeds:
        opt = build_options(
            15, root_dir=str(tmp_path / f"s{seed}"), num_actors=2,
            steps=1500, seed=seed,
            learn_start=32, batch_size=16, memory_size=8192, seq_len=16,
            seq_overlap=8, nstep=3, actor_sync_freq=20,
            param_publish_freq=5, learner_freq=50, evaluator_freq=2,
            max_replay_ratio=32.0, lr=1e-3, target_model_update=100,
            early_stop=200, eps=0.7, eps_alpha=3.0)
        runtime.train(opt, backend="thread")
        opt2 = build_options(15, root_dir=str(tmp_path / f"s{seed}"),
                             mode=2, tester_nepisodes=5, seq_len=16,
                             model_file=opt.model_name)
        last = runtime.test(opt2)
        if (last["nepisodes_solved"] == 5.0
                and last["avg_reward"] >= 0.9 and last["avg_steps"] <= 10):
            break
        if seed != seeds[-1]:
            print(f"[test] seed {seed} missed the bar ({last}); "
                  f"retrying with the next seed")
    assert last["nepisodes_solved"] == 5.0
    assert last["avg_reward"] >= 0.9
    assert last["avg_steps"] <= 10
