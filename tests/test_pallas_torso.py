"""ISSUE-13 Pallas fused dqn-cnn torso: interpret-mode parity against
the XLA reference (forward AND gradients, bf16 and fp32), the matmul
kernel's tiling/VJP contract, the factory's loud-downgrade gate, and
the MXU-filling wide torso family's lane alignment.  On CPU the kernels
run under the Pallas interpreter; a real TPU compiles the same kernels
(ops/pallas_torso.py docstring)."""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from pytorch_distributed_tpu.models import DqnCnnModel, DqnCnnWideModel
from pytorch_distributed_tpu.ops.pallas_torso import (
    build_pallas_torso_apply, make_mxu_matmul,
)


@pytest.fixture(scope="module")
def cnn_setup():
    model = DqnCnnModel(action_space=6, norm_val=255.0,
                        compute_dtype=jnp.float32)
    obs = np.random.default_rng(0).integers(
        0, 255, (2, 4, 84, 84)).astype(np.uint8)
    params = model.init(jax.random.PRNGKey(0), obs)
    return model, params, obs


class TestMxuMatmul:
    def test_matches_jnp_dot_on_unaligned_shapes(self):
        # 100x70 @ 70x33: none of M/K/N on the 128 grid — the padding
        # path must be invisible in the result
        mm = make_mxu_matmul(interpret=True)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(100, 70)).astype(np.float32)
        w = rng.normal(size=(70, 33)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(mm(x, w)), x @ w,
                                   rtol=1e-5, atol=1e-5)

    def test_custom_vjp_matches_jnp_grads(self):
        mm = make_mxu_matmul(interpret=True)
        rng = np.random.default_rng(2)
        x = rng.normal(size=(16, 40)).astype(np.float32)
        w = rng.normal(size=(40, 24)).astype(np.float32)
        f_pal = lambda x, w: jnp.sum(mm(x, w) ** 2)
        f_ref = lambda x, w: jnp.sum((x @ w) ** 2)
        gx_p, gw_p = jax.grad(f_pal, argnums=(0, 1))(x, w)
        gx_r, gw_r = jax.grad(f_ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_r),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gw_p), np.asarray(gw_r),
                                   rtol=1e-4, atol=1e-4)


class TestTorsoParity:
    def test_forward_parity_fp32(self, cnn_setup):
        model, params, obs = cnn_setup
        ap = build_pallas_torso_apply(norm_val=255.0,
                                      compute_dtype=jnp.float32,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(ap(params, obs)),
                                   np.asarray(model.apply(params, obs)),
                                   rtol=1e-4, atol=1e-4)

    def test_grad_parity_fp32(self, cnn_setup):
        model, params, obs = cnn_setup
        ap = build_pallas_torso_apply(norm_val=255.0,
                                      compute_dtype=jnp.float32,
                                      interpret=True)
        # a loss shaped like the DQN TD loss (sum of squared Q): grads
        # flow through every conv + dense kernel and bias
        g_ref = jax.grad(lambda p: jnp.sum(model.apply(p, obs) ** 2))(
            params)
        g_pal = jax.grad(lambda p: jnp.sum(ap(p, obs) ** 2))(params)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3),
            g_ref, g_pal)

    def test_forward_parity_bf16(self):
        model = DqnCnnModel(action_space=6, norm_val=255.0,
                            compute_dtype=jnp.bfloat16)
        obs = np.random.default_rng(3).integers(
            0, 255, (2, 4, 84, 84)).astype(np.uint8)
        params = model.init(jax.random.PRNGKey(0), obs)
        ap = build_pallas_torso_apply(norm_val=255.0,
                                      compute_dtype=jnp.bfloat16,
                                      interpret=True)
        q_ref = np.asarray(model.apply(params, obs))
        q_pal = np.asarray(ap(params, obs))
        # bf16 rounding between layers differs (the kernel accumulates
        # fp32 and rounds once per GEMM; XLA's conv may round more
        # often) — parity is at bf16 resolution, not fp32
        np.testing.assert_allclose(q_pal, q_ref, rtol=0.05, atol=0.05)

    def test_grad_parity_bf16(self):
        model = DqnCnnModel(action_space=6, norm_val=255.0,
                            compute_dtype=jnp.bfloat16)
        obs = np.random.default_rng(4).integers(
            0, 255, (2, 4, 84, 84)).astype(np.uint8)
        params = model.init(jax.random.PRNGKey(0), obs)
        ap = build_pallas_torso_apply(norm_val=255.0,
                                      compute_dtype=jnp.bfloat16,
                                      interpret=True)
        g_ref = jax.grad(lambda p: jnp.mean(model.apply(p, obs) ** 2))(
            params)
        g_pal = jax.grad(lambda p: jnp.mean(ap(p, obs) ** 2))(params)
        flat_r = ravel_pytree(g_ref)[0]
        flat_p = ravel_pytree(g_pal)[0]
        # cosine agreement: bf16 per-element tolerances are vacuous on
        # near-zero grads; direction agreement across the whole tree is
        # the trainability contract
        cos = float(jnp.dot(flat_r, flat_p)
                    / (jnp.linalg.norm(flat_r) * jnp.linalg.norm(flat_p)))
        assert cos > 0.999, cos

    def test_forward_parity_non_square_frames(self):
        """H != W observations: _patches must derive the output width
        from the input WIDTH (a review-caught bug had it slicing both
        spatial axes off the height)."""
        model = DqnCnnModel(action_space=5, norm_val=255.0,
                            compute_dtype=jnp.float32)
        obs = np.random.default_rng(5).integers(
            0, 255, (2, 4, 84, 108)).astype(np.uint8)
        params = model.init(jax.random.PRNGKey(2), obs)
        ap = build_pallas_torso_apply(norm_val=255.0,
                                      compute_dtype=jnp.float32,
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(ap(params, obs)),
                                   np.asarray(model.apply(params, obs)),
                                   rtol=1e-4, atol=1e-4)

    def test_nhwc_input_variant(self, cnn_setup):
        model, params, obs = cnn_setup
        nhwc_model = model.clone(nhwc_input=True)
        obs_nhwc = np.transpose(obs, (0, 2, 3, 1))
        ap = build_pallas_torso_apply(norm_val=255.0,
                                      compute_dtype=jnp.float32,
                                      nhwc_input=True, interpret=True)
        np.testing.assert_allclose(
            np.asarray(ap(params, obs_nhwc)),
            np.asarray(nhwc_model.apply(params, obs_nhwc)),
            rtol=1e-4, atol=1e-4)


class TestFactoryGate:
    def _opt(self, **over):
        from pytorch_distributed_tpu.config import build_options

        return build_options(4, **over)  # pong-sim dqn-cnn row

    def test_off_by_default_keeps_model_apply(self):
        from pytorch_distributed_tpu.factory import _dqn_train_apply

        opt = self._opt()
        model = DqnCnnModel(action_space=6)
        assert _dqn_train_apply(opt, model) == model.apply

    def test_cpu_without_interpret_downgrades_loudly(self):
        from pytorch_distributed_tpu.factory import _dqn_train_apply

        opt = self._opt(pallas_torso=True)
        model = DqnCnnModel(action_space=6)
        with pytest.warns(UserWarning, match="no TPU backend"):
            apply_fn = _dqn_train_apply(opt, model)
        assert apply_fn == model.apply

    def test_interpret_knob_swaps_the_torso(self):
        from pytorch_distributed_tpu.factory import _dqn_train_apply

        opt = self._opt(pallas_torso=True, pallas_interpret=True)
        model = DqnCnnModel(action_space=6,
                            compute_dtype=jnp.float32)
        apply_fn = _dqn_train_apply(opt, model)
        assert apply_fn is not model.apply
        obs = np.zeros((1, 4, 84, 84), np.uint8)
        params = model.init(jax.random.PRNGKey(0), obs)
        q = apply_fn(params, obs)
        assert q.shape == (1, 6) and q.dtype == jnp.float32

    def test_wrong_model_type_warns_and_keeps_xla(self):
        from pytorch_distributed_tpu.factory import _dqn_train_apply
        from pytorch_distributed_tpu.models import DqnMlpModel

        opt = self._opt(pallas_torso=True)
        opt.model_type = "dqn-mlp"
        model = DqnMlpModel(action_space=3)
        with pytest.warns(UserWarning, match="dqn-cnn torso only"):
            assert _dqn_train_apply(opt, model) == model.apply


class TestWideTorso:
    def test_lane_alignment_and_shapes(self):
        model = DqnCnnWideModel(action_space=6,
                                compute_dtype=jnp.float32)
        obs = np.random.default_rng(0).integers(
            0, 255, (2, 4, 20, 20)).astype(np.uint8)
        params = model.init(jax.random.PRNGKey(0), obs)
        q = model.apply(params, obs)
        assert q.shape == (2, 6) and q.dtype == jnp.float32
        # the family's reason to exist: every conv output-channel width
        # is a multiple of the 128 MXU lanes
        def widths(tree, prefix=""):
            for k, v in tree.items():
                if k == "kernel" and v.ndim == 4:
                    yield v.shape[-1]
                elif isinstance(v, dict):
                    yield from widths(v, prefix + k + "/")
        for w in widths(params["params"]):
            assert w % 128 == 0, w

    def test_trains_through_dqn_step(self):
        from pytorch_distributed_tpu.ops.losses import (
            build_dqn_train_step, init_train_state, make_optimizer,
        )
        from pytorch_distributed_tpu.utils.experience import Batch

        model = DqnCnnWideModel(action_space=4,
                                compute_dtype=jnp.float32)
        rng = np.random.default_rng(1)
        obs = lambda n: rng.integers(0, 255, (n, 4, 20, 20)).astype(
            np.uint8)
        params = model.init(jax.random.PRNGKey(0), obs(1))
        tx = make_optimizer(1e-3)
        state = init_train_state(params, tx)
        step = jax.jit(build_dqn_train_step(model.apply, tx))
        B = 4
        batch = Batch(state0=obs(B),
                      action=rng.integers(0, 4, B).astype(np.int32),
                      reward=rng.normal(size=B).astype(np.float32),
                      gamma_n=np.full(B, 0.95, np.float32),
                      state1=obs(B),
                      terminal1=np.zeros(B, np.float32),
                      weight=np.ones(B, np.float32),
                      index=np.arange(B, dtype=np.int32))
        new_state, metrics, td = step(state, batch)
        assert int(new_state.step) == 1
        assert np.isfinite(float(metrics["learner/critic_loss"]))

    def test_factory_registration(self):
        from pytorch_distributed_tpu.config import CONFIGS, build_options
        from pytorch_distributed_tpu.factory import build_model

        row = CONFIGS[19]
        assert row[4] == "dqn-cnn-wide"
        opt = build_options(19)
        # probe-free spec: the pong-sim CNN geometry is static
        from pytorch_distributed_tpu.factory import EnvSpec

        spec = EnvSpec(state_shape=(4, 84, 84), discrete=True,
                       num_actions=6, action_dim=0, norm_val=255.0)
        model = build_model(opt, spec)
        assert isinstance(model, DqnCnnWideModel)
        assert model.width == opt.model_params.cnn_wide_width == 128
