"""Elastic multi-learner replica plane (ISSUE 15).

Three test tiers:

- **Registry/lease units** (numpy, milliseconds): monotonic
  generations, expiry, double-lease fencing, round-stall expulsion of a
  hung-but-renewing member, stale-generation gradient/priority rejects,
  the join barrier, and the decorrelated redial-jitter satellite.
- **Wire drills**: the same machinery through a real ``DcnGateway``
  over loopback — lease verbs, a two-client reduced round, fenced
  zombies, the no-registry error leg, and the fleet_top replicas panel.
- **The degraded-parity oracle** (jax, tier-1 acceptance): a 2-replica
  CPU run that loses one replica at round K must produce params
  bit-identical — every leaf, plus the PER priorities and the
  key-stream schedule — to the solo learner from the degradation round
  onward under a fixed seed; and the dead replica's stale-generation
  write-back is a counted reject that touches nothing.
- **Slow**: the real-topology kill→degrade→rejoin acceptance drill —
  two spawned replica learner processes, one SIGKILLed mid-run through
  the production ``REPLICA_FAULTS`` plane, a replacement rejoining at a
  new generation through the checkpoint-epoch barrier.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.config import ReplicaParams, build_options
from pytorch_distributed_tpu.parallel.dcn import (
    RSTAT_FENCED, RSTAT_NOREG, RSTAT_OK, RSTAT_STALE, DcnGateway,
    LocalReplicaChannel, ReplicaClient, ReplicaFenced, ReplicaRegistry,
    redial_backoff, resolve_replica,
)

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _registry(replicas=2, lease_s=0.4, **kw) -> ReplicaRegistry:
    return ReplicaRegistry(ReplicaParams(replicas=replicas,
                                         lease_s=lease_s, **kw))


def _gateway(registry=None):
    store = ParamStore(4)
    store.publish(np.zeros(4, dtype=np.float32))
    return DcnGateway(store, GlobalClock(), ActorStats(),
                      put_chunk=lambda items: None, host="127.0.0.1",
                      port=0, replicas=registry)


# ---------------------------------------------------------------------------
# lease-fenced membership units
# ---------------------------------------------------------------------------

class TestLeaseMembership:
    def test_acquire_grants_monotonic_generations(self):
        reg = _registry()
        g1 = reg.acquire(0, incarnation=10)["generation"]
        g2 = reg.acquire(1, incarnation=10)["generation"]
        assert g2 > g1
        assert sorted(reg.status_block()["members"]) == ["0", "1"]

    def test_missed_lease_expires_and_fences(self):
        reg = _registry(lease_s=0.15)
        reg.acquire(0, incarnation=1)
        time.sleep(0.3)
        reg.renew(0, -1)  # any registry op runs the expiry pass
        assert reg.leases_expired == 1
        assert reg.status_block()["members"] == {}
        assert reg.status_block()["degraded"]

    def test_renew_extends_and_expired_renew_says_so(self):
        reg = _registry(lease_s=0.3)
        g = reg.acquire(0, incarnation=1)["generation"]
        for _ in range(4):
            time.sleep(0.15)
            assert reg.renew(0, g)["status"] == "ok"
        time.sleep(0.6)
        assert reg.renew(0, g)["status"] == "expired"
        assert reg.leases_expired == 1

    def test_double_lease_newer_incarnation_wins(self):
        """Same slot, two incarnations: the newer incarnation evicts
        (counted fence), the older/equal one is refused — PR 1's slot
        fencing lifted to the learner plane."""
        reg = _registry()
        g_old = reg.acquire(0, incarnation=5)["generation"]
        assert reg.acquire(0, incarnation=5)["status"] == "refused"
        assert reg.acquire(0, incarnation=4)["status"] == "refused"
        r = reg.acquire(0, incarnation=6)
        assert r["status"] == "ok" and r["generation"] > g_old
        assert reg.lease_fenced == 1
        # the fenced generation can no longer write anything
        res = reg.submit(0, g_old, 0, np.zeros(2, np.float32))
        assert res["status"] in (RSTAT_FENCED, RSTAT_STALE)
        assert reg.stale_grad_rejected == 1

    def test_release_shrinks_membership_immediately(self):
        reg = _registry()
        g = reg.acquire(0, incarnation=1)["generation"]
        reg.acquire(1, incarnation=1)
        reg.release(0, g)
        assert reg.leases_released == 1
        assert sorted(reg.status_block()["members"]) == ["1"]


class TestRoundExchange:
    def _pair(self, reg):
        a = LocalReplicaChannel(reg, 0)
        b = LocalReplicaChannel(reg, 1)
        a.acquire()
        b.acquire()
        return a, b

    def test_round_reduces_mean_in_replica_order(self):
        reg = _registry(lease_s=5.0)
        a, b = self._pair(reg)
        out = [None, None]

        def run(ch, i, v):
            out[i] = ch.submit_round(
                0, np.asarray([v, v], np.float32),
                pidx=np.asarray([i], np.int32),
                ptd=np.asarray([0.5 + i], np.float32))

        t = threading.Thread(target=run, args=(b, 1, 3.0), daemon=True)
        t.start()
        run(a, 0, 1.0)
        t.join(5)
        assert out[0]["status"] == RSTAT_OK
        assert np.array_equal(out[0]["grad"],
                              np.asarray([2.0, 2.0], np.float32))
        assert np.array_equal(out[0]["grad"], out[1]["grad"])
        assert out[0]["members"] == [0, 1]
        # merged write-backs: one group per contributor, ascending id,
        # identical on both replies
        assert [w[0] for w in out[0]["writebacks"]] == [0, 1]
        assert [(w[0], list(w[1])) for w in out[0]["writebacks"]] == \
            [(w[0], list(w[1])) for w in out[1]["writebacks"]]

    def test_expiry_mid_round_completes_over_survivors(self):
        """B contributes to round 0 then dies (no renew): A's round 1
        must complete over {A} within one lease window, and the reduce
        is A's own gradient bit-for-bit (mean over one contributor)."""
        reg = _registry(lease_s=0.3)
        a, b = self._pair(reg)
        out = [None, None]

        def run0(ch, i):
            out[i] = ch.submit_round(0, np.ones(2, np.float32) * (i + 1))

        t = threading.Thread(target=run0, args=(b, 1), daemon=True)
        t.start()
        run0(a, 0)
        t.join(5)
        assert out[0]["status"] == RSTAT_OK
        # B goes silent (its renewer never ran); A's next round fences it
        g = np.asarray([7.5, -2.25], np.float32)
        t0 = time.monotonic()
        res = a.submit_round(1, g)
        took = time.monotonic() - t0
        assert res["status"] == RSTAT_OK
        assert res["members"] == [0]
        assert np.array_equal(res["grad"], g)  # mean over {A} == A's grad
        assert took < 3 * 0.3 + 1.0  # within the lease-window contract
        assert reg.leases_expired == 1
        assert reg.degraded_completions == 1

    def test_hung_but_renewing_member_is_round_stalled(self):
        """The hang mode: a member whose renewer faithfully renews but
        whose round loop is frozen.  Leases prove liveness, rounds
        prove progress — the registry's round-stall rule must expel it
        within one lease window and count the expiry."""
        reg = _registry(lease_s=0.3)
        a, b = self._pair(reg)
        b.start_renewer(period=0.05)  # B renews forever, submits never
        res = a.submit_round(0, np.ones(2, np.float32))
        assert res["status"] == RSTAT_OK
        assert res["members"] == [0]
        assert reg.leases_expired == 1
        b.close()
        # the expelled member's next submit is fenced, counted
        out = reg.submit(1, b.generation, 1, np.zeros(2, np.float32))
        assert out["status"] in (RSTAT_FENCED, RSTAT_STALE)
        assert reg.stale_grad_rejected == 1

    def test_stale_generation_prio_writeback_rejected(self):
        reg = _registry(lease_s=0.15)
        a, b = self._pair(reg)
        a.start_renewer(period=0.04)  # A stays live through the sleep
        dead_gen = b.generation
        time.sleep(0.35)  # B never renews: lease expires
        a.renew()
        assert reg.leases_expired >= 1
        res = reg.merge_prio(1, dead_gen, np.asarray([3], np.int32),
                             np.asarray([9.9], np.float32))
        assert res["status"] == "stale"
        assert reg.stale_prio_rejected == 1
        # a LIVE generation's out-of-round write-back queues for the
        # next round's merged reply instead
        ok = reg.merge_prio(0, a.generation,
                            np.asarray([1], np.int32),
                            np.asarray([0.5], np.float32))
        assert ok["status"] == "ok" and reg.prio_merged_rows == 1
        out = a.submit_round(0, np.zeros(2, np.float32))
        assert (0, [1]) in [(w[0], list(w[1])) for w in
                            out["writebacks"]]
        a.close()

    def test_rejoin_after_sigkill_new_generation_via_barrier(self):
        """Kill = the channel vanishes without release; the replacement
        acquires at a NEW generation, the survivors' barrier round
        carries ``epoch_due``, and after activation the membership (and
        round numbering) is whole again."""
        reg = _registry(lease_s=0.25, join_timeout_s=10.0)
        a, b = self._pair(reg)
        out = [None, None]

        def run0(ch, i):
            out[i] = ch.submit_round(0, np.ones(2, np.float32))

        t = threading.Thread(target=run0, args=(b, 1), daemon=True)
        t.start()
        run0(a, 0)
        t.join(5)
        dead_gen = b.generation  # B is SIGKILLed here: no release
        # A trains on alone; B's lease expires, rounds go degraded
        assert a.submit_round(1, np.ones(2, np.float32))["members"] \
            == [0]

        # the replacement: new channel, same slot, NEW generation
        b2 = LocalReplicaChannel(reg, 1)
        reply = b2.acquire()
        assert reply["generation"] > dead_gen
        barrier = reply["epoch_barrier"]
        assert barrier is not None and reply["round"] == barrier + 1
        b2.start_renewer(period=0.05)

        committed = {}

        def survivor():
            r = 2
            while r <= barrier + 1:
                res = a.submit_round(r, np.full(2, float(r),
                                                np.float32))
                assert res["status"] == RSTAT_OK
                if res["epoch_due"]:
                    committed["step"] = r + 1
                    a.note_epoch(r, r + 1)
                r += 1
            committed["final_members"] = res["members"]

        ts = threading.Thread(target=survivor, daemon=True)
        ts.start()
        # the joiner: poll for the barrier epoch, "load" it, activate,
        # then contribute its entry round
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            j = b2.poll_join()
            if j and j.get("epoch_step") is not None:
                break
            time.sleep(0.02)
        assert j and j["epoch_step"] == committed["step"]
        b2.activate(j["epoch_step"])
        res = b2.submit_round(barrier + 1,
                              np.full(2, float(barrier + 1),
                                      np.float32))
        ts.join(10)
        assert res["status"] == RSTAT_OK
        assert res["members"] == [0, 1]
        assert committed["final_members"] == [0, 1]
        assert reg.joins_completed == 1
        assert reg.leases_expired == 1
        # the zombie's stale generation still bounces
        z = reg.submit(1, dead_gen, barrier + 1,
                       np.zeros(2, np.float32))
        assert z["status"] in (RSTAT_FENCED, RSTAT_STALE)


# ---------------------------------------------------------------------------
# the reconnect thundering-herd satellite
# ---------------------------------------------------------------------------

class TestRedialJitter:
    def _seq(self, slot, n=8):
        rng = np.random.default_rng((0xDC2, slot))
        d, seq = 0.05, []
        for _ in range(n):
            d = redial_backoff(rng, d)
            seq.append(d)
        return seq

    def test_slots_spread_their_redial_times(self):
        assert self._seq(0) != self._seq(1)
        assert self._seq(3) != self._seq(4)

    def test_deterministic_per_slot_and_bounded(self):
        """Seeded drills stay reproducible: the schedule is a pure
        function of the slot, and every delay respects [base, cap]."""
        assert self._seq(2) == self._seq(2)
        for d in self._seq(5, n=32):
            assert 0.05 <= d <= 1.0

    def test_dcn_client_carries_a_slot_seeded_stream(self):
        gw = _gateway()
        try:
            from pytorch_distributed_tpu.parallel.dcn import DcnClient

            c0 = DcnClient(("127.0.0.1", gw.port), process_ind=0)
            c1 = DcnClient(("127.0.0.1", gw.port), process_ind=1)
            try:
                d0 = [redial_backoff(c0._redial_rng, 0.05)
                      for _ in range(4)]
                d1 = [redial_backoff(c1._redial_rng, 0.05)
                      for _ in range(4)]
                assert d0 != d1
            finally:
                c0.close()
                c1.close()
        finally:
            gw.close()


# ---------------------------------------------------------------------------
# wire drills + the fleet_top replicas panel
# ---------------------------------------------------------------------------

class TestReplicaWire:
    def test_lease_and_round_over_the_wire(self):
        reg = _registry(lease_s=1.0)
        gw = _gateway(reg)
        try:
            a = ReplicaClient(("127.0.0.1", gw.port), 0)
            b = ReplicaClient(("127.0.0.1", gw.port), 1)
            a.acquire()
            b.acquire()
            assert a.generation != b.generation
            out = [None, None]

            def run(ch, i, v):
                out[i] = ch.submit_round(
                    0, np.asarray([v], np.float32),
                    pidx=np.asarray([i], np.int32),
                    ptd=np.asarray([1.0], np.float32))

            t = threading.Thread(target=run, args=(b, 1, 4.0),
                                 daemon=True)
            t.start()
            run(a, 0, 2.0)
            t.join(5)
            for o in out:
                assert o["status"] == RSTAT_OK
                assert np.array_equal(o["grad"],
                                      np.asarray([3.0], np.float32))
                assert o["members"] == [0, 1]
                assert len(o["writebacks"]) == 2
            a.release()
            b.release()
            a.close()
            b.close()
        finally:
            gw.close()

    def test_stale_generation_fenced_over_the_wire(self):
        reg = _registry(lease_s=0.2)
        gw = _gateway(reg)
        try:
            a = ReplicaClient(("127.0.0.1", gw.port), 0)
            a.acquire()
            dead = a.generation
            time.sleep(0.5)  # expire (no renewer started)
            res = a.submit_round(0, np.zeros(2, np.float32))
            assert res["status"] in (RSTAT_FENCED, RSTAT_STALE)
            assert a.fenced.is_set()
            z = ReplicaClient(("127.0.0.1", gw.port), 0)
            z.generation = dead
            assert z.merge_prio(np.asarray([0], np.int32),
                                np.asarray([1.0], np.float32)
                                )["status"] == "stale"
            z.close()
            a.close()
            assert reg.stale_grad_rejected == 1
            assert reg.stale_prio_rejected == 1
        finally:
            gw.close()

    def test_registryless_gateway_answers_errors_not_crashes(self):
        gw = _gateway(None)
        try:
            c = ReplicaClient(("127.0.0.1", gw.port), 0)
            with pytest.raises(ReplicaFenced):
                c.acquire()
            c.generation = 1
            res = c.submit_round(0, np.zeros(2, np.float32))
            assert res["status"] == RSTAT_NOREG
            c.close()
        finally:
            gw.close()

    def test_fleet_top_replicas_panel_and_json(self):
        """The satellite: the STATUS ``replicas`` block round-trips the
        wire, renders as a panel line, stays JSON-serializable, and
        shouts DEGRADED when membership is short."""
        import tools.fleet_top as ft
        from pytorch_distributed_tpu.parallel.dcn import fetch_status

        reg = _registry(replicas=2, lease_s=5.0)
        gw = _gateway(reg)
        try:
            a = LocalReplicaChannel(reg, 0)
            a.acquire()
            status = fetch_status(("127.0.0.1", gw.port))
            assert "replicas" in status
            json.dumps(status)  # the --json path must stay serializable
            line = ft.replicas_line(status)
            assert line is not None and "replicas:" in line
            assert "1/2" in line and "DEGRADED" in line
            assert "r0" in line and "gen" in line
            b = LocalReplicaChannel(reg, 1)
            b.acquire()
            status = fetch_status(("127.0.0.1", gw.port))
            line = ft.replicas_line(status)
            assert "2/2" in line and "DEGRADED" not in line
            # the whole panel renders with the replicas row in place
            assert "replicas:" in ft.render(status)
        finally:
            gw.close()

    def test_resolve_replica_env_contract(self, monkeypatch):
        monkeypatch.setenv("TPU_APEX_REPLICA_REPLICAS", "3")
        monkeypatch.setenv("TPU_APEX_REPLICA_LEASE_S", "2.5")
        rp = resolve_replica()
        assert rp.replicas == 3
        assert rp.lease_s == 2.5
        base = ReplicaParams(lease_s=9.0)
        assert resolve_replica(base).lease_s == 2.5  # env wins
        assert base.lease_s == 9.0  # input never mutated


# ---------------------------------------------------------------------------
# chaos drills through the production fault plane (tier-1 smoke)
# ---------------------------------------------------------------------------

class TestReplicaChaosDrills:
    @pytest.mark.timeout(120)
    def test_kill_then_rejoin_drill_exits_clean(self):
        """The acceptance drill: membership shrinks on the kill, the
        membership alert fires, the replacement rejoins through the
        epoch barrier, the alert resolves, and every fencing/ledger
        counter is exact — zero violations."""
        sys.path.insert(0, os.path.join(_TESTS_DIR, os.pardir))
        from tools.chaos_soak import replica_soak

        report = replica_soak(replicas=2, rounds=45, seed=3, kill_at=8,
                              rejoin=True, verbose=False)
        assert report["violations"] == []
        assert report["counters"]["stale_grad_rejected"] == 1
        assert report["counters"]["stale_prio_rejected"] == 1
        assert report["alerts"]["fired"] == ["replica_degraded"]
        assert report["alerts"]["unresolved"] == []

    @pytest.mark.slow
    @pytest.mark.timeout(300)
    def test_hang_replica_drill_exits_clean(self):
        sys.path.insert(0, os.path.join(_TESTS_DIR, os.pardir))
        from tools.chaos_soak import replica_soak

        report = replica_soak(replicas=2, rounds=60, seed=5,
                              hang_at=10, rejoin=True, verbose=False)
        assert report["violations"] == []


# ---------------------------------------------------------------------------
# the degraded-parity oracle (jax; tier-1 acceptance)
# ---------------------------------------------------------------------------

def _oracle_opt(tmp_path, refs="replicas-oracle"):
    opt = build_options(
        1, root_dir=str(tmp_path), refs=refs, seed=11,
        hidden_dim=32, batch_size=8, memory_size=128, learn_start=32,
        steps=10_000, replicas=2,
        evaluator_nepisodes=0)
    # lease_s lives on both the replica and gateway planes (ISSUE 16),
    # so the bare build_options override is ambiguous — set it directly
    opt.replica_params.lease_s = 0.6
    return opt


class TestDegradedParityOracle:
    @pytest.mark.timeout(600)
    def test_survivor_bit_identical_to_solo_from_degradation(
            self, tmp_path):
        """THE acceptance oracle: 2 replicas train through the real
        registry; replica 1 is killed at round K (stops submitting AND
        renewing — the in-process image of SIGKILL).  The survivor's
        trajectory from round K onward must be bit-identical — every
        param leaf, the full PER ring priorities, and the key-stream
        schedule — to a solo driver seeded with the survivor's state at
        the degradation boundary.  Plus: the zombie's stale-generation
        priority write-back after the kill is rejected-and-counted and
        perturbs nothing (the survivor's priorities still match the
        solo leg that never saw it)."""
        import jax

        from pytorch_distributed_tpu.agents.learner import (
            ReplicaLearnerDriver,
        )
        from pytorch_distributed_tpu.factory import probe_env

        opt = _oracle_opt(tmp_path)
        spec = probe_env(opt)
        reg = ReplicaRegistry(resolve_replica(opt.replica_params))
        chA = LocalReplicaChannel(reg, 0)
        chB = LocalReplicaChannel(reg, 1)
        dA = ReplicaLearnerDriver(opt, spec, 0, chA)
        dB = ReplicaLearnerDriver(opt, spec, 1, chB)
        chA.acquire()
        chB.acquire()
        chA.start_renewer(period=0.1)
        chB.start_renewer(period=0.1)
        dA.members = [0, 1]
        dB.members = [0, 1]
        dA.prefill(64)
        dB.prefill(64)

        K, T = 4, 9
        traj = {}

        def cap_a(r, drv):
            traj[r] = drv.snapshot()

        def run_b():
            dB.run_rounds(K)   # rounds 0..K-1, then "SIGKILL"
            chB.close()        # the renewer dies with the process

        tb = threading.Thread(target=run_b, daemon=True)
        tb.start()
        dA.run_rounds(T, capture=cap_a)
        tb.join(30)
        assert not tb.is_alive()
        chA.close()
        assert reg.leases_expired == 1
        assert reg.degraded_completions >= 1
        assert dA.members == [0]

        # two-replica rounds really were two-replica (the merge carried
        # both write-back groups), degraded rounds carried one
        assert len(traj) == T

        # ---- zombie leg: the dead generation is fenced, counted, and
        # side-effect-free
        dead_gen = chB.generation
        z = reg.merge_prio(1, dead_gen, np.asarray([0], np.int32),
                           np.asarray([99.0], np.float32))
        assert z["status"] == "stale"
        assert reg.stale_prio_rejected == 1
        zg = reg.submit(1, dead_gen, T - 1, np.zeros(2, np.float32))
        assert zg["status"] in (RSTAT_FENCED, RSTAT_STALE)

        # ---- solo leg: same construction, N=1 registry, seeded with
        # the survivor's state at the degradation boundary
        reg2 = ReplicaRegistry(ReplicaParams(replicas=1, lease_s=5.0))
        chS = LocalReplicaChannel(reg2, 0)
        dS = ReplicaLearnerDriver(opt, spec, 0, chS)
        chS.acquire()
        chS.start_renewer(period=0.5)
        dS.load_snapshot(traj[K - 1])
        dS.members = [0]
        solo = {}
        dS.run_rounds(T, capture=lambda r, drv:
                      solo.__setitem__(r, drv.snapshot()))
        chS.close()

        for r in range(K, T):
            a_leaves = jax.tree_util.tree_leaves(traj[r]["state"])
            s_leaves = jax.tree_util.tree_leaves(solo[r]["state"])
            assert len(a_leaves) == len(s_leaves)
            for x, y in zip(a_leaves, s_leaves):
                assert np.array_equal(np.asarray(x), np.asarray(y)), \
                    f"param leaf diverged at round {r}"
            ra, rs = traj[r]["ring"], solo[r]["ring"]
            assert np.array_equal(np.asarray(ra.priority),
                                  np.asarray(rs.priority)), \
                f"PER priorities diverged at round {r}"
            assert np.array_equal(np.asarray(ra.max_priority),
                                  np.asarray(rs.max_priority))
        # the key-stream schedule: the survivor at rank 0 of {0} drew
        # the EXACT keys the solo driver drew
        ka, ks = dict(dA.key_log), dict(dS.key_log)
        for r in range(K, T):
            assert np.array_equal(ka[r], ks[r]), \
                f"key stream diverged at round {r}"


# ---------------------------------------------------------------------------
# slow: the real-topology kill -> degrade -> rejoin acceptance drill
# ---------------------------------------------------------------------------

class TestRealTopologyReplicaDrill:
    @pytest.mark.slow
    @pytest.mark.timeout(840)
    def test_sigkill_degrade_rejoin_on_real_processes(self, tmp_path):
        """Two REAL replica learner processes against a real gateway:
        replica 1 SIGKILLs itself at round 25 through the production
        ``REPLICA_FAULTS`` plane, the survivor degrades (counted), a
        replacement process rejoins at a new generation through the
        checkpoint-epoch barrier, and a SIGTERM preemption drains both
        to clean exits with a committed final epoch."""
        from pytorch_distributed_tpu.utils import checkpoint as ckpt

        reg = ReplicaRegistry(ReplicaParams(
            replicas=2, lease_s=1.5, join_timeout_s=120.0))
        gw = _gateway(reg)
        child_py = os.path.join(_TESTS_DIR, "_replica_child.py")
        refs = "replicadrill"

        def spawn(rid, faults=""):
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            env.pop("REPLICA_FAULTS", None)
            if faults:
                env["REPLICA_FAULTS"] = faults
            return subprocess.Popen(
                [sys.executable, child_py,
                 "--coordinator", f"127.0.0.1:{gw.port}",
                 "--replica-id", str(rid),
                 "--root-dir", str(tmp_path), "--refs", refs],
                env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT)

        def wait_for(pred, timeout, what):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if pred():
                    return
                time.sleep(0.2)
            raise AssertionError(f"timed out waiting for {what}")

        procs = []
        try:
            p0 = spawn(0)
            p1 = spawn(1, faults="kill@25")
            procs = [p0, p1]
            wait_for(lambda: len(reg.status_block()["members"]) == 2,
                     300, "both replicas to lease")
            wait_for(lambda: reg.rounds_completed > 0, 300,
                     "the first completed round")
            # the production fault plane SIGKILLs replica 1 at round 25
            wait_for(lambda: p1.poll() is not None, 300,
                     "the kill@25 SIGKILL")
            assert p1.returncode == -signal.SIGKILL
            wait_for(lambda: reg.leases_expired >= 1, 60,
                     "the dead lease to expire")
            wait_for(lambda: reg.degraded_completions >= 1, 60,
                     "a degraded round completion")
            # the replacement: same slot, new generation, epoch barrier
            p1b = spawn(1)
            procs.append(p1b)
            wait_for(lambda: reg.joins_completed == 1, 420,
                     "the rejoin to activate through the epoch barrier")
            wait_for(
                lambda: len(reg.status_block()["members"]) == 2, 60,
                "membership to recover")
            r_mark = reg.rounds_completed
            wait_for(lambda: reg.rounds_completed > r_mark + 3, 120,
                     "post-rejoin rounds at N=2")
            # preemption: both drain, commit, release, exit 0
            p0.send_signal(signal.SIGTERM)
            p1b.send_signal(signal.SIGTERM)
            for p in (p0, p1b):
                p.wait(timeout=180)
                assert p.returncode == 0, \
                    p.stdout.read().decode(errors="replace")[-2000:]
            assert reg.leases_released == 2
            info = ckpt.resolve_epoch(
                os.path.join(str(tmp_path), "models", refs))
            assert info is not None and info.learner_step > 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(10)
                if p.stdout:
                    p.stdout.close()
            gw.close()
