"""Data-plane X-ray (ISSUE 8): transition provenance round-trips across
every hop (assembler, spawn-queue pickling, DCN wire, host sidecars,
device ring columns, checkpoint snapshots), staleness math under
ParamPrefetcher version bumps, the priority X-ray (host/device bucket
parity + the detector's ESS-collapse signal), quarantine correlation
keys, and the acceptance drill: a CPU PER topology with TPU_APEX_PERF=1
exports learner/staleness, learner/sample_age, replay/actor_share and
the priority histogram live (scalars.jsonl + fleet STATUS data
gauges)."""

import json
import os
import pickle
import sys
import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.memory.feeder import QueueOwner
from pytorch_distributed_tpu.memory.prioritized import PrioritizedReplay
from pytorch_distributed_tpu.ops.nstep import NStepAssembler
from pytorch_distributed_tpu.parallel.dcn import (
    decode_chunk, encode_chunk, fetch_status,
)
from pytorch_distributed_tpu.utils import (
    flight_recorder, health, perf, tracing,
)
from pytorch_distributed_tpu.utils.experience import (
    PROV_FIELDS, Transition, make_prov, stack_prov,
)
from pytorch_distributed_tpu.utils.metrics import read_scalars

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_registries(monkeypatch):
    for var in list(os.environ):
        if var == "TPU_APEX_PERF" or var.startswith("TPU_APEX_PERF_"):
            monkeypatch.delenv(var, raising=False)
    perf.reset()
    tracing.reset()
    flight_recorder.reset()
    health.reset()
    yield
    perf.reset()
    tracing.reset()
    flight_recorder.reset()
    health.reset()


def _mk_transition(v: float, prov=None) -> Transition:
    return Transition(
        state0=np.full((4,), v, np.float32), action=np.int32(int(v) % 3),
        reward=np.float32(v), gamma_n=np.float32(0.99),
        state1=np.full((4,), v + 1, np.float32),
        terminal1=np.float32(0.0), prov=prov)


# ---------------------------------------------------------------------------
# minting + transport
# ---------------------------------------------------------------------------

class TestMintingAndTransport:
    def test_assembler_prov_rides_the_window_fifo(self):
        """Provenance is minted at ACTION time and emitted with the
        window that opened on that action — including the shrinking
        terminal tail, where several windows (each with its own birth
        tick) flush at once."""
        a = NStepAssembler(3, 0.99)
        out = []
        for t in range(6):
            out += a.feed(np.zeros(2), np.int32(0), 1.0, np.ones(2),
                          t == 5, prov=make_prov(4, 1, 7, 100 + t))
        assert len(out) == 6
        assert [int(tr.prov[3]) for tr in out] == [100 + i
                                                   for i in range(6)]
        assert all(tuple(tr.prov[:3]) == (4, 1, 7) for tr in out)

    def test_spawn_queue_pickling_preserves_prov(self):
        chunk = tracing.TracedChunk(
            [(_mk_transition(i, make_prov(2, i, 5, 10 + i)), 0.5)
             for i in range(4)])
        clone = pickle.loads(pickle.dumps(chunk))  # the spawn-queue hop
        assert isinstance(clone, tracing.TracedChunk)
        assert clone.trace_id == chunk.trace_id
        for i, (t, _p) in enumerate(clone):
            assert tuple(t.prov) == (2, i, 5, 10 + i)

    def test_dcn_wire_round_trip_mixed_rows(self):
        """The savez wire carries provenance as an (n, 4) int64 column;
        rows minted without provenance survive as None, and a chunk
        with NO provenance at all ships byte-compatible (no column)."""
        items = [(_mk_transition(0, make_prov(1, 0, 3, 50)), 1.0),
                 (_mk_transition(1, None), None),
                 (_mk_transition(2, make_prov(1, 2, 3, 52)), 0.25)]
        dec = decode_chunk(encode_chunk(items))
        assert tuple(dec[0][0].prov) == (1, 0, 3, 50)
        assert dec[1][0].prov is None
        assert tuple(dec[2][0].prov) == (1, 2, 3, 52)
        legacy = [(_mk_transition(9, None), None)]
        import io

        with np.load(io.BytesIO(encode_chunk(legacy))) as z:
            assert "prov" not in z.files  # legacy wire bytes unchanged

    def test_malformed_prov_column_is_rejected(self):
        items = [(_mk_transition(0, make_prov(1, 0, 3, 50)), 1.0)]
        payload = encode_chunk(items)
        import io

        with np.load(io.BytesIO(payload)) as z:
            cols = {k: z[k] for k in z.files}
        cols["prov"] = cols["prov"][:, :2]  # wrong width
        out = io.BytesIO()
        np.savez(out, **cols)
        with pytest.raises(ValueError, match="prov"):
            decode_chunk(out.getvalue())


# ---------------------------------------------------------------------------
# storage sidecars + checkpoints
# ---------------------------------------------------------------------------

class TestHostSidecars:
    def test_prioritized_sidecar_sample_and_snapshot_round_trip(self):
        mem = PrioritizedReplay(capacity=16, state_shape=(4,),
                                state_dtype=np.float32)
        for i in range(10):
            mem.feed(_mk_transition(i, make_prov(i % 3, i, 2, 100 + i)),
                     0.5)
        rng = np.random.default_rng(0)
        batch = mem.sample(8, rng)
        prov = mem.provenance_of(batch.index)
        assert prov.shape == (8, len(PROV_FIELDS))
        for row, idx in zip(prov, batch.index):
            assert tuple(row) == (idx % 3, idx, 2, 100 + idx)
        # checkpoint epoch leg: snapshot -> (savez round trip) -> restore
        snap = mem.snapshot()
        assert snap["prov"].shape == (10, 4)
        import io

        buf = io.BytesIO()
        np.savez(buf, **snap)
        buf.seek(0)
        with np.load(buf) as z:
            data = {k: z[k] for k in z.files}
        fresh = PrioritizedReplay(capacity=16, state_shape=(4,),
                                  state_dtype=np.float32)
        fresh.restore(data)
        np.testing.assert_array_equal(fresh.provenance_of(np.arange(10)),
                                      mem.provenance_of(np.arange(10)))
        # a pre-provenance snapshot restores to the -1 sentinel
        legacy = {k: v for k, v in data.items() if k != "prov"}
        fresh2 = PrioritizedReplay(capacity=16, state_shape=(4,),
                                   state_dtype=np.float32)
        fresh2.restore(legacy)
        assert (fresh2.provenance_of(np.arange(10)) == -1).all()

    def test_queue_owner_delegates_provenance(self):
        owner = QueueOwner(PrioritizedReplay(capacity=8, state_shape=(4,),
                                             state_dtype=np.float32))
        f = owner.make_feeder(chunk=2)
        for i in range(4):
            f.feed(_mk_transition(i, make_prov(0, i, 1, i)), 0.5)
        f.flush()
        # mp.Queue hands chunks to its feeder thread asynchronously: a
        # single drain-until-empty pass can land BETWEEN two chunks'
        # visibility and under-read the queue (observed on this image:
        # rows [2, 3] still in flight -> -1 provenance sentinels), so
        # poll until every row has arrived
        drained = 0
        deadline = time.monotonic() + 10.0
        while drained < 4:
            drained += owner.drain()
            if drained < 4:
                assert time.monotonic() < deadline, \
                    f"only {drained}/4 rows drained"
                time.sleep(0.01)
        np.testing.assert_array_equal(
            owner.provenance_of(np.arange(4))[:, 3], np.arange(4))
        assert owner.priority_leaves() is not None

    def test_sequence_replay_sidecar(self):
        from pytorch_distributed_tpu.memory.sequence_replay import (
            Segment, SequenceReplay,
        )

        rep = SequenceReplay(capacity=4, seq_len=5, state_shape=(3,),
                             lstm_dim=2, priority_exponent=0.9)
        seg = Segment(obs=np.zeros((6, 3), np.float32),
                      action=np.zeros(5, np.int32),
                      reward=np.zeros(5, np.float32),
                      terminal=np.zeros(5, np.float32),
                      mask=np.ones(5, np.float32),
                      c0=np.zeros(2, np.float32),
                      h0=np.zeros(2, np.float32),
                      prov=make_prov(3, 1, 9, 77))
        rep.feed(seg, 0.5)
        assert tuple(rep.provenance_of([0])[0]) == (3, 1, 9, 77)
        snap = rep.snapshot()
        fresh = SequenceReplay(capacity=4, seq_len=5, state_shape=(3,),
                               lstm_dim=2, priority_exponent=0.9)
        fresh.restore(snap)
        assert tuple(fresh.provenance_of([0])[0]) == (3, 1, 9, 77)


class TestDeviceRingColumns:
    def test_ring_columns_feed_sample_snapshot_restore(self):
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplay, provenance_sample,
        )
        import jax

        ring = DeviceReplay(capacity=32, state_shape=(4,),
                            state_dtype=np.float32)
        n = 12
        prov = np.stack([make_prov(i % 2, i, 3, 200 + i)
                         for i in range(n)]).astype(np.int32)
        chunk = Transition(
            state0=np.zeros((n, 4), np.float32),
            action=np.zeros((n,), np.int32),
            reward=np.arange(n, dtype=np.float32),
            gamma_n=np.full((n,), 0.9, np.float32),
            state1=np.zeros((n, 4), np.float32),
            terminal1=np.zeros((n,), np.float32),
            prov=prov)
        ring.feed_chunk(chunk)
        got, fill = provenance_sample(ring.state, jax.random.PRNGKey(0),
                                      n=64)
        got = np.asarray(got)
        assert int(fill) == n
        assert (got[:, 0] >= 0).all()  # every drawn row was stamped
        assert set(got[:, 3].tolist()) <= set((200 + np.arange(n))
                                              .tolist())
        snap = ring.snapshot()
        np.testing.assert_array_equal(snap["prov"], prov.astype(np.int64))
        fresh = DeviceReplay(capacity=32, state_shape=(4,),
                             state_dtype=np.float32)
        fresh.restore(snap)
        np.testing.assert_array_equal(fresh.snapshot()["prov"],
                                      prov.astype(np.int64))
        # a legacy chunk (no prov) recycles slots back to the sentinel
        ring.feed_chunk(chunk._replace(prov=None))
        snap2 = ring.snapshot()
        assert (snap2["prov"][-n:] == -1).all()

    def test_fused_replay_rollout_stamps_ring_columns(self):
        """The emit="replay" fused rollout scatters (actor_id, env_slot,
        param_version, birth_step) alongside each emitted row; env_slot
        is the env's row index."""
        import jax
        import jax.numpy as jnp

        from pytorch_distributed_tpu.envs.device_env import (
            build_device_env,
        )
        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplay,
        )
        from pytorch_distributed_tpu.models.policies import (
            build_fused_rollout, init_rollout_carry,
        )

        opt = build_options(4, visualize=False)
        N, K, NSTEP = 4, 8, 5
        env = build_device_env(opt.env_params, 0, N)

        def linear_apply(params, obs):
            x = obs.reshape(obs.shape[0], -1).astype(jnp.float32)
            return x @ params["w"]

        params = {"w": jnp.zeros((4 * 84 * 84, 6), jnp.float32)}
        ring = DeviceReplay(capacity=256, state_shape=env.state_shape,
                            state_dtype=np.uint8)
        roll = build_fused_rollout(linear_apply, env, nstep=NSTEP,
                                   gamma=0.99, rollout_ticks=K,
                                   emit="replay")
        carry = init_rollout_carry(env, NSTEP)
        eps = jnp.full((N,), 0.1, jnp.float32)
        key = jnp.asarray(jax.random.PRNGKey(0))
        prov3 = jnp.asarray(np.array([2, 41, 1234], np.int32))
        carry, rs, stats = roll(params, carry, ring.state, key,
                                jnp.int32(0), eps, prov3)
        fed = int(jax.device_get(stats.fed))
        assert fed == (K - NSTEP) * N
        pv = np.asarray(jax.device_get(rs.prov))[:fed]
        assert (pv[:, 0] == 2).all()
        assert (pv[:, 2] == 41).all()
        assert (pv[:, 3] == 1234).all()
        # rows land tick-major: env_slot cycles 0..N-1 per tick
        np.testing.assert_array_equal(
            pv[:, 1], np.tile(np.arange(N), K - NSTEP))


class TestHostVsDeviceEmitParity:
    @pytest.mark.slow
    def test_device_and_host_emit_mint_identical_provenance(
            self, tmp_path):
        """ISSUE 8 satellite: under a fixed param version and learner
        clock, the fused device rollout path and the host
        NStepAssembler emit path mint BIT-IDENTICAL provenance for the
        same (actor, env-slot) stream positions.  The transition
        streams themselves are pinned identical by the PR-7 parity
        chain (tests/test_device_env.py — the inline leg there steps a
        CounterRng-patched twin env, which bounded runs don't), so the
        provenance claim reduces to both paths minting the same
        deterministic (actor_id, env_slot, version, birth) pattern
        over their emission order — asserted against the closed-form
        expectation on a REAL device bounded run (dqn-cnn fused
        rollout) and a REAL inline bounded run (host assembler path,
        fake-env geometry where it is cheap)."""
        from pytorch_distributed_tpu.agents.actor import (
            bounded_actor_run,
        )

        N = 4
        # device leg: the fused rollout driver's per-dispatch stamps
        opt = build_options(
            4, root_dir=str(tmp_path), refs="prov_dev", num_actors=1,
            num_envs_per_actor=N, actor_backend="device",
            visualize=False, actor_freq=10 ** 9,
            actor_sync_freq=10 ** 9)
        opt.env_params.device_rollout_ticks = 4
        dev = bounded_actor_run(opt, ticks=3, param_seed=0)["stream"]
        # inline leg: the host assembler's per-tick mints over the same
        # game (no episode boundary falls inside 12 Pong ticks, so both
        # paths sit in pure steady state)
        opt2 = build_options(
            4, root_dir=str(tmp_path), refs="prov_inl", num_actors=1,
            num_envs_per_actor=N, actor_backend="inline",
            visualize=False, actor_freq=10 ** 9,
            actor_sync_freq=10 ** 9)
        inl = bounded_actor_run(opt2, ticks=12, param_seed=0)["stream"]
        assert len(dev) >= 20 and len(inl) >= 20

        def expected(stream):
            # post-warmup every tick emits one row per env, env-slot
            # cycling 0..N-1; version is the single published snapshot
            # (1), birth the frozen learner clock (0)
            return [make_prov(0, i % N, 1, 0)
                    for i in range(len(stream))]

        for stream in (dev, inl):
            for (t, _p), want in zip(stream, expected(stream)):
                assert t.prov is not None
                np.testing.assert_array_equal(np.asarray(t.prov), want)


# ---------------------------------------------------------------------------
# staleness math + priority X-ray + detector
# ---------------------------------------------------------------------------

class TestStalenessAndXray:
    def test_staleness_under_prefetcher_version_bumps(self):
        from pytorch_distributed_tpu.agents.param_store import (
            ParamPrefetcher, ParamStore,
        )

        store = ParamStore(4)
        v1 = store.publish(np.zeros(4, np.float32))
        pf = ParamPrefetcher(store, lambda flat: flat,
                             start_version=v1, poll_secs=0.01)
        try:
            v2 = store.publish(np.ones(4, np.float32))
            deadline = time.monotonic() + 5.0
            got = None
            while got is None and time.monotonic() < deadline:
                got = pf.take()
                time.sleep(0.01)
            assert got is not None
            _tree, version = got
            assert version == v2
        finally:
            pf.close()
        # the learner-side subtraction: rows minted pre-bump read as
        # one version stale, post-bump rows as fresh
        prov = np.stack([make_prov(0, 0, v1, 10),
                         make_prov(0, 1, v2, 20)])
        staleness = np.maximum(store.version - prov[:, 2], 0)
        np.testing.assert_array_equal(staleness, [1, 0])

    def test_priority_xray_host_math(self):
        uniform = health.priority_xray(np.full(100, 0.5))
        assert uniform["rows"] == 100
        assert uniform["ess"] == pytest.approx(100.0)
        assert uniform["ess_frac"] == pytest.approx(1.0)
        assert uniform["counts"].sum() == 100
        spiked = health.priority_xray(
            np.concatenate([np.full(99, 1e-6), [100.0]]))
        assert spiked["ess_frac"] < 0.05  # one row dominates
        assert health.priority_xray(np.zeros(8)) is None

    def test_priority_xray_device_matches_host_buckets(self):
        import jax

        from pytorch_distributed_tpu.memory.device_per import (
            DevicePerReplay, priority_xray_device,
        )

        mem = DevicePerReplay(capacity=32, state_shape=(4,),
                              state_dtype=np.float32)
        n = 16
        mem.feed_chunk(Transition(
            state0=np.zeros((n, 4), np.float32),
            action=np.zeros((n,), np.int32),
            reward=np.zeros((n,), np.float32),
            gamma_n=np.full((n,), 0.9, np.float32),
            state1=np.zeros((n, 4), np.float32),
            terminal1=np.zeros((n,), np.float32)))
        leaves = np.asarray(jax.device_get(mem.state.priority))
        counts, ess, rows, mass = jax.device_get(
            priority_xray_device(mem.state))
        host = health.priority_xray(leaves[leaves > 0])
        assert int(rows) == host["rows"] == n
        assert float(ess) == pytest.approx(host["ess"], rel=1e-5)
        assert float(mass) == pytest.approx(host["mass"], rel=1e-5)
        np.testing.assert_array_equal(np.asarray(counts),
                                      host["counts"])

    def test_detector_fires_on_ess_collapse(self):
        det = health.AnomalyDetector(threshold=1, ess_floor=0.05)
        assert "priority_collapse" not in det.observe(
            priority_mass=10.0, replay_rows=100, priority_ess=0.5)
        out = det.observe(priority_mass=10.0, replay_rows=100,
                          priority_ess=0.01)
        assert "priority_collapse" in out  # healthy mass, collapsed ESS


# ---------------------------------------------------------------------------
# quarantine correlation keys (satellite 6)
# ---------------------------------------------------------------------------

class TestQuarantineCorrelation:
    def test_quarantine_file_carries_run_id_wall_and_prov(self, tmp_path):
        flight_recorder.configure(str(tmp_path), run_id="drill_run_7")
        store = health.QuarantineStore("test-src")
        bad = [(_mk_transition(0, make_prov(5, 2, 3, 99)), float("nan"),
                "non-finite reward")]
        path = store.put(bad, trace_id=0xabc)
        assert path is not None
        with np.load(path, allow_pickle=False) as z:
            cols = {k: z[k] for k in z.files}
        assert str(cols["run_id"][0]) == "drill_run_7"
        assert cols["wall"][0] > 0
        np.testing.assert_array_equal(cols["prov"][0], [5, 2, 3, 99])

    def test_stack_prov_mixed(self):
        rows = stack_prov([(_mk_transition(0, make_prov(1, 2, 3, 4)), 0.1),
                           (_mk_transition(1, None), None)])
        np.testing.assert_array_equal(rows,
                                      [[1, 2, 3, 4], [-1, -1, -1, -1]])

    def test_stack_prov_accepts_bare_transitions(self):
        """Transition IS a NamedTuple (a tuple): stack_prov must not
        unwrap it as an (item, priority) pair — that would read state0
        and silently sentinel every stamped row (the review-caught bug
        that killed provenance on the device-ring ingest path)."""
        rows = stack_prov([_mk_transition(0, make_prov(9, 8, 7, 6)),
                           _mk_transition(1, None)])
        np.testing.assert_array_equal(rows,
                                      [[9, 8, 7, 6], [-1, -1, -1, -1]])

    def test_device_ingest_drain_stamps_ring_columns(self):
        """End to end over the host-actor -> device-ring path: a
        QueueFeeder chunk of stamped transitions drained by
        DeviceReplayIngest must land in the HBM ring's provenance
        columns, not as sentinels."""
        import jax

        from pytorch_distributed_tpu.memory.device_replay import (
            DeviceReplayIngest, provenance_sample,
        )

        ing = DeviceReplayIngest(capacity=64, state_shape=(4,),
                                 state_dtype=np.float32, chunk_size=4)
        feeder = ing.make_feeder(chunk=4)
        ing.attach(mesh=None)
        for i in range(8):
            feeder.feed(_mk_transition(i, make_prov(1, i % 4, 2, 30 + i)),
                        None)
        feeder.flush()
        deadline = time.monotonic() + 10.0
        while ing.size < 8 and time.monotonic() < deadline:
            ing.drain()
            time.sleep(0.02)
        assert ing.size == 8
        pv, _fill = provenance_sample(ing.replay.state,
                                      jax.random.PRNGKey(0), n=32)
        pv = np.asarray(pv)
        assert (pv[:, 0] == 1).all()      # no sentinels: stamps survived
        assert (pv[:, 2] == 2).all()
        assert set(pv[:, 3].tolist()) <= set(range(30, 38))

    def test_shared_replay_unwritten_rows_read_unknown(self):
        from pytorch_distributed_tpu.memory.shared_replay import (
            SharedReplay,
        )

        mem = SharedReplay(capacity=8, state_shape=(4,),
                           state_dtype=np.float32)
        mem.feed(_mk_transition(0, make_prov(1, 2, 3, 4)))
        mem.feed(_mk_transition(1, None))
        got = mem.provenance_of(np.arange(8))
        np.testing.assert_array_equal(got[0], [1, 2, 3, 4])
        # unwritten pages are zeroed mp.Arrays — they must still read
        # as the -1 sentinel, never as "actor 0, version 0"
        assert (got[1:] == -1).all()


# ---------------------------------------------------------------------------
# bench gate wiring (satellite: provenance_overhead under the overhead band)
# ---------------------------------------------------------------------------

class TestBenchGateWiring:
    def test_provenance_overhead_gated_with_absolute_band(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import bench_gate

        assert any(p == "provenance_overhead.provenance_overhead_frac"
                   and d == "lower_abs" and s == "overhead"
                   for p, d, s in bench_gate.SPECS)
        base = {"provenance_overhead": {"provenance_overhead_frac": 0.001}}
        ok = {"provenance_overhead": {"provenance_overhead_frac": 0.015}}
        bad = {"provenance_overhead": {"provenance_overhead_frac": 0.05}}
        assert not bench_gate.compare(ok, base)["regressions"]
        report = bench_gate.compare(bad, base)
        assert [r["key"] for r in report["regressions"]] == \
            ["provenance_overhead.provenance_overhead_frac"]

    def test_bench_exposes_provenance_mode(self):
        import bench

        assert hasattr(bench, "bench_provenance_overhead")
        # the smoke variant shares the measurement logic (CI-sized)
        import inspect

        assert "smoke" in inspect.signature(
            bench.bench_provenance_overhead).parameters


# ---------------------------------------------------------------------------
# fleet_top data line
# ---------------------------------------------------------------------------

class TestFleetTopDataLine:
    def test_data_line_renders_from_perf_gauges(self):
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        import fleet_top

        status = {"perf": {"learner": {
            "data/staleness_p50": 2.0, "data/sample_age_p95": 140.0,
            "data/priority_ess": 0.42, "data/top_actor_share": 0.3}}}
        line = fleet_top.data_line(status)
        assert "staleness p50 2" in line
        assert "sample age p95 140" in line
        assert "priority ESS 42%" in line
        assert "top actor 30%" in line
        vals = fleet_top.data_values(status)
        assert vals["data/priority_ess"] == 0.42
        assert fleet_top.data_line({}) is None


# ---------------------------------------------------------------------------
# acceptance: live data plane on a short CPU PER topology
# ---------------------------------------------------------------------------

class TestDataPlaneAcceptance:
    def test_cpu_per_topology_exports_data_plane_live(self, tmp_path,
                                                      monkeypatch):
        """ISSUE 8 acceptance: a CPU topology run with TPU_APEX_PERF=1
        exports learner/staleness, learner/sample_age,
        replay/actor_share histogram rows and the priority X-ray
        (buckets row + replay/priority_ess) to the metrics stream, and
        the STATUS perf block carries the live data/* gauges fleet_top
        renders."""
        monkeypatch.setenv("TPU_APEX_PERF", "1")
        from pytorch_distributed_tpu.fleet import FleetTopology

        opt = build_options(
            1, memory_type="prioritized", root_dir=str(tmp_path),
            refs="provrun", num_actors=1, seed=5,
            steps=10 ** 9, max_seconds=120.0, max_replay_ratio=16.0,
            learn_start=32, memory_size=512, batch_size=16,
            actor_freq=25, actor_sync_freq=50, param_publish_freq=25,
            learner_freq=25, logger_freq=2, evaluator_nepisodes=0,
            early_stop=50, checkpoint_freq=0)
        topo = FleetTopology(opt, local_actors=1, port=0)
        done = threading.Event()

        def run():
            try:
                topo.run(backend="thread")
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        addr = ("127.0.0.1", topo.port)
        status = None
        try:
            deadline = time.monotonic() + 100
            while time.monotonic() < deadline and not done.is_set():
                try:
                    status = fetch_status(addr, timeout=5.0)
                except (ConnectionError, OSError):
                    status = None
                lsnap = (status or {}).get("perf", {}).get("learner", {})
                if "data/staleness_p50" in lsnap:
                    break
                time.sleep(0.25)
        finally:
            topo.clock.stop.set()
            t.join(120)
        assert not t.is_alive()
        lsnap = (status or {}).get("perf", {}).get("learner", {})
        assert "data/staleness_p50" in lsnap, \
            f"data gauges never reached STATUS (have {sorted(lsnap)})"
        assert "data/priority_ess" in lsnap
        assert 0 < lsnap["data/priority_ess"] <= 1.0
        assert "data/top_actor_share" in lsnap

        rows = read_scalars(opt.log_dir)
        hists = {r["tag"] for r in rows if r.get("kind") == "histogram"}
        for tag in ("learner/staleness", "learner/sample_age",
                    "replay/actor_share"):
            assert tag in hists, f"{tag} histogram missing"
        buckets = [r for r in rows if r.get("kind") == "buckets"
                   and r["tag"] == "replay/priority"]
        assert buckets, "priority X-ray buckets row missing"
        assert sum(buckets[-1]["counts"]) == buckets[-1]["rows"]
        ess_rows = [r for r in rows
                    if r.get("tag") == "replay/priority_ess_frac"]
        assert ess_rows and all(0 < r["value"] <= 1.0 for r in ess_rows)
        # staleness is version-denominated and sane: p50 gauge is a
        # small non-negative number (actors lag by at most a few
        # publishes at these cadences)
        assert lsnap["data/staleness_p50"] >= 0
