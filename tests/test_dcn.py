"""DCN transport tests: wire codec, gateway<->client round trips, and a
fleet run with remote actors over localhost — the multi-host topology
exercised in-process (SURVEY.md §4 calls for multi-node simulation; the
reference has no multi-host anything to test)."""

import threading
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.config import build_options
from pytorch_distributed_tpu.agents.clocks import ActorStats, GlobalClock
from pytorch_distributed_tpu.agents.param_store import ParamStore
from pytorch_distributed_tpu.parallel.dcn import (
    DcnClient, DcnGateway, RemoteClock, RemoteMemory, RemoteParamStore,
    RemoteStats, decode_chunk, encode_chunk,
)
from pytorch_distributed_tpu.utils.experience import Transition


def _transition(i: int, shape=(4,)) -> Transition:
    return Transition(
        state0=np.full(shape, i, dtype=np.float32),
        action=np.int32(i % 3),
        reward=np.float32(0.5 * i),
        gamma_n=np.float32(0.99),
        state1=np.full(shape, i + 1, dtype=np.float32),
        terminal1=np.float32(i % 2),
    )


class TestChunkCodec:
    def test_round_trip_preserves_fields_and_priorities(self):
        items = [(_transition(i), None if i % 2 else float(i)) for i in
                 range(5)]
        out = decode_chunk(encode_chunk(items))
        assert len(out) == 5
        for (t0, p0), (t1, p1) in zip(items, out):
            for f in Transition._fields:
                np.testing.assert_array_equal(np.asarray(getattr(t0, f)),
                                              np.asarray(getattr(t1, f)))
            assert (p0 is None) == (p1 is None)
            if p0 is not None:
                assert p0 == pytest.approx(p1)

    def test_uint8_states_survive(self):
        t = Transition(
            state0=np.arange(8, dtype=np.uint8).reshape(2, 4),
            action=np.int32(1), reward=np.float32(1.0),
            gamma_n=np.float32(0.9),
            state1=np.arange(8, 16, dtype=np.uint8).reshape(2, 4),
            terminal1=np.float32(0.0))
        [(t1, _)] = decode_chunk(encode_chunk([(t, None)]))
        assert t1.state0.dtype == np.uint8
        np.testing.assert_array_equal(t1.state0, t.state0)


@pytest.fixture()
def gateway():
    clock = GlobalClock()
    stats = ActorStats()
    store = ParamStore(16)
    chunks = []
    gw = DcnGateway(store, clock, stats, put_chunk=chunks.append,
                    host="127.0.0.1", port=0)
    yield gw, store, clock, stats, chunks
    gw.close()


class TestGateway:
    def test_experience_flows_to_put_chunk(self, gateway):
        gw, _store, _clock, _stats, chunks = gateway
        client = DcnClient(("127.0.0.1", gw.port))
        mem = RemoteMemory(client, chunk=3)
        for i in range(7):
            mem.feed(_transition(i), None)
        mem.flush()
        client.close()
        deadline = time.monotonic() + 5
        while sum(len(c) for c in chunks) < 7:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        got = [t for c in chunks for t, _ in c]
        assert len(got) == 7
        np.testing.assert_array_equal(got[4].state0,
                                      np.full((4,), 4, dtype=np.float32))

    def test_param_fetch_versions(self, gateway):
        gw, store, _clock, _stats, _chunks = gateway
        client = DcnClient(("127.0.0.1", gw.port))
        ps = RemoteParamStore(client)
        assert ps.fetch(0) is None  # nothing published yet
        flat0 = np.arange(16, dtype=np.float32)
        store.publish(flat0)
        flat, version = ps.wait(0, timeout=5)
        assert version == 1
        np.testing.assert_array_equal(flat, flat0)
        assert ps.fetch(version) is None  # no newer snapshot
        store.publish(flat0 * 2)
        flat2, v2 = ps.fetch(version)
        assert v2 == 2
        np.testing.assert_array_equal(flat2, flat0 * 2)
        client.close()

    def test_clock_and_stats_aggregate(self, gateway):
        gw, _store, clock, stats, _chunks = gateway
        client = DcnClient(("127.0.0.1", gw.port))
        rclock = RemoteClock(client, flush_every=4)
        rstats = RemoteStats(client)
        for _ in range(9):
            rclock.add_actor_steps(1)
        rclock.flush()
        assert clock.actor_step.value == 9
        rstats.add(nepisodes=2, total_reward=5.0)
        drained = stats.drain()
        assert drained["nepisodes"] == 2
        assert drained["total_reward"] == pytest.approx(5.0)
        # learner step propagates back; stop flag terminates done()
        clock.set_learner_step(123)
        rclock.flush()
        assert rclock.learner_step.value == 123
        assert rclock.done(steps=100)
        assert not client.stop.is_set()
        clock.stop.set()
        rclock.flush()
        assert client.stop.is_set()
        client.close()

    def test_client_disconnects_on_gateway_death(self, gateway):
        gw, _store, _clock, _stats, _chunks = gateway
        client = DcnClient(("127.0.0.1", gw.port), heartbeat_interval=0,
                           reconnect_timeout=1.0)
        rclock = RemoteClock(client, flush_every=1)
        gw.close()
        # the next flush hits a dead socket and no gateway ever returns:
        # the reconnect budget burns out into the DISCONNECTED state —
        # never the stop flag, which is reserved for "learner said stop"
        # (a gateway blip must not read as a completed run)
        deadline = time.monotonic() + 30
        while not client.disconnected.is_set():
            rclock.add_actor_steps(1)
            assert time.monotonic() < deadline
            time.sleep(0.05)
        assert not client.stop.is_set()
        assert rclock.done(steps=10 ** 9)
        assert rclock._pending >= 1  # failed ticks re-queued, not dropped


class TestSlotLifecycle:
    def test_slot_freed_on_disconnect_then_reclaimable(self, gateway):
        gw, *_ = gateway
        c1 = DcnClient(("127.0.0.1", gw.port), process_ind=5,
                       heartbeat_interval=0)
        assert gw.active_slots == {5: c1.incarnation}
        c1.close()
        deadline = time.monotonic() + 5
        while gw.active_slots:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        c2 = DcnClient(("127.0.0.1", gw.port), process_ind=5,
                       heartbeat_interval=0)
        assert gw.active_slots == {5: c2.incarnation}
        c2.close()

    def test_hello_conflict_on_live_duplicate(self, gateway):
        gw, *_ = gateway
        c1 = DcnClient(("127.0.0.1", gw.port), process_ind=3,
                       incarnation=200, heartbeat_interval=0)
        # equal (or lower) incarnation = a genuine duplicate actor, the
        # epsilon-schedule-skewing config error: refused outright
        with pytest.raises(RuntimeError, match="already connected"):
            DcnClient(("127.0.0.1", gw.port), process_ind=3,
                      incarnation=200, heartbeat_interval=0)
        assert gw.active_slots == {3: 200}  # original claim untouched
        assert gw.fenced == 0
        c1.tick(actor_steps=1)  # and still live
        c1.close()

    def test_fencing_evicts_lower_incarnation_predecessor(self, gateway):
        gw, *_ = gateway
        a = DcnClient(("127.0.0.1", gw.port), process_ind=3,
                      incarnation=100, heartbeat_interval=0,
                      reconnect_timeout=1.0)
        b = DcnClient(("127.0.0.1", gw.port), process_ind=3,
                      incarnation=200, heartbeat_interval=0)
        assert gw.fenced == 1
        assert gw.active_slots == {3: 200}
        b.tick(actor_steps=1)  # the higher incarnation owns the slot
        # the fenced-off predecessor cannot reclaim: its reconnect
        # arrives at incarnation 101 < 200 and is terminally refused
        with pytest.raises(ConnectionError):
            a.tick(actor_steps=1)
        assert a.disconnected.is_set() and not a.stop.is_set()
        a.close()
        time.sleep(0.2)
        assert gw.active_slots == {3: 200}  # identity-checked release
        b.close()

    def test_local_slot_refused(self):
        clock = GlobalClock()
        gw = DcnGateway(ParamStore(16), clock, ActorStats(),
                        put_chunk=lambda items: None,
                        host="127.0.0.1", port=0, local_actors=2)
        try:
            with pytest.raises(RuntimeError,
                               match="local to the learner host"):
                DcnClient(("127.0.0.1", gw.port), process_ind=1,
                          heartbeat_interval=0)
        finally:
            gw.close()


class TestFleetEndToEnd:
    @pytest.mark.slow
    @pytest.mark.timeout(900)
    def test_remote_actors_train_over_localhost(self, tmp_path):
        """Learner host (thread backend, 0 local actors) + 2 remote actors
        on localhost: the full Ape-X loop with every shared-plane mechanism
        replaced by the DCN protocol."""
        from pytorch_distributed_tpu.fleet import (
            FleetTopology, _remote_actor_main,
        )

        opt = build_options(
            1, num_actors=2, root_dir=str(tmp_path), seed=7,
            steps=30, learn_start=20, memory_size=512, batch_size=16,
            actor_freq=25, actor_sync_freq=20, param_publish_freq=10,
            learner_freq=10, evaluator_freq=1, evaluator_nepisodes=1,
            checkpoint_freq=0, early_stop=50,
        )
        topo = FleetTopology(opt, local_actors=0, port=0)
        actors = [
            threading.Thread(
                target=_remote_actor_main,
                args=(opt, f"127.0.0.1:{topo.port}", ind), daemon=True)
            for ind in range(2)
        ]
        for t in actors:
            t.start()
        topo.run(backend="thread")
        for t in actors:
            t.join(30)
            assert not t.is_alive()
        assert topo.clock.learner_step.value >= 30
        assert topo.clock.actor_step.value > 0
        assert topo.gateway.chunks_in > 0
