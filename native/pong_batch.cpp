// Batched Pong simulator: N independent games stepped in one C call.
//
// First-party native env stepper — the second natural native component
// SURVEY.md §2 identifies ("a C++ batched env stepper replacing the
// per-process Python ALE loop"; the reference itself ships zero first-party
// native code).  Game dynamics, rendering, and the observation pipeline are
// bit-compatible with the pure-Python simulator in
// pytorch_distributed_tpu/envs/pong_sim.py: 84x84 grayscale uint8 frames,
// action-repeat K with a max-pool over the last two raw frames, hist-length
// frame stack, scoring to 21 (the preprocessing contract of reference
// core/envs/atari_env.py:53-61,89-104).  Dynamics between scoring events are
// deterministic doubles, so tests can set identical state on both
// implementations and require bit-exact frames.
//
// Python's round() is round-half-to-even; rendering replicates it (py_round)
// so frames match the Python simulator exactly.
//
// Auto-reset semantics match envs/vector.py: when game i ends, step()
// returns the *reset* observation for i and deposits the true terminal
// observation in the final_obs buffer (the n-step assembler must see the
// real boundary, not the reset frame).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr double H = 84.0, W = 84.0;
constexpr double PADDLE_H = 10.0, PADDLE_W = 2.0, BALL = 2.0;
constexpr double PLAYER_X = W - 6.0, ENEMY_X = 4.0;
constexpr double PLAYER_SPEED = 2.0, ENEMY_SPEED = 0.9;
constexpr double BALL_SPEED_X = 1.4;
constexpr int WIN_SCORE = 21;
constexpr int FRAME = 84 * 84;

// action -> vertical move (NOOP/FIRE/UP/DOWN/UPFIRE/DOWNFIRE)
const double MOVE[6] = {0.0, 0.0, -PLAYER_SPEED, +PLAYER_SPEED,
                        -PLAYER_SPEED, +PLAYER_SPEED};

inline double clipd(double v, double lo, double hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

// Python round(): banker's rounding (half to even).
inline double py_round(double x) {
  double r = std::nearbyint(x);  // default FP env rounds half-to-even
  return r == 0.0 ? 0.0 : r;     // normalize -0
}

// splitmix64 -> uniform doubles; per-env stream.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next_u64() {
    uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  double uniform() {  // [0, 1)
    return (next_u64() >> 11) * 0x1.0p-53;
  }
  double uniform(double lo, double hi) { return lo + (hi - lo) * uniform(); }
};

struct Game {
  double player_y, enemy_y, ball_x, ball_y, ball_vx, ball_vy;
  int score_enemy, score_player;
  int64_t episode_steps;  // agent steps, for early_stop truncation
  Rng rng;

  explicit Game(uint64_t seed) : rng(seed) { reset(); }

  void reset_ball(int direction) {
    ball_x = W / 2;
    ball_y = rng.uniform(20.0, H - 20.0);
    ball_vx = BALL_SPEED_X * direction;
    ball_vy = rng.uniform(-1.2, 1.2);
  }

  void reset() {
    score_enemy = score_player = 0;
    episode_steps = 0;
    player_y = H / 2;
    enemy_y = H / 2;
    int dir = rng.uniform() < 0.5 ? 1 : -1;  // matches pong_sim.py:_reset
    reset_ball(dir);
  }

  // one raw frame; returns the player's scoring reward
  double tick(double move) {
    player_y = clipd(player_y + move, PADDLE_H / 2, H - PADDLE_H / 2);
    double err = ball_y - enemy_y;
    enemy_y = clipd(enemy_y + clipd(err, -ENEMY_SPEED, ENEMY_SPEED),
                    PADDLE_H / 2, H - PADDLE_H / 2);

    ball_x += ball_vx;
    ball_y += ball_vy;
    if (ball_y < BALL / 2) {
      ball_y = BALL - ball_y;
      ball_vy = -ball_vy;
    } else if (ball_y > H - BALL / 2) {
      ball_y = 2 * (H - BALL / 2) - ball_y;
      ball_vy = -ball_vy;
    }

    if (ball_vx > 0 && ball_x >= PLAYER_X - PADDLE_W &&
        std::fabs(ball_y - player_y) <= PADDLE_H / 2 + BALL / 2) {
      ball_x = PLAYER_X - PADDLE_W;
      ball_vx = -ball_vx;
      ball_vy += 0.5 * (ball_y - player_y) / (PADDLE_H / 2);
      ball_vy = clipd(ball_vy, -2.0, 2.0);
    } else if (ball_vx < 0 && ball_x <= ENEMY_X + PADDLE_W &&
               std::fabs(ball_y - enemy_y) <= PADDLE_H / 2 + BALL / 2) {
      ball_x = ENEMY_X + PADDLE_W;
      ball_vx = -ball_vx;
      ball_vy += 0.5 * (ball_y - enemy_y) / (PADDLE_H / 2);
      ball_vy = clipd(ball_vy, -2.0, 2.0);
    }

    if (ball_x < 0) {
      score_player += 1;
      reset_ball(-1);
      return 1.0;
    }
    if (ball_x > W) {
      score_enemy += 1;
      reset_ball(1);
      return -1.0;
    }
    return 0.0;
  }

  void draw(uint8_t* f) const {
    std::memset(f, 35, FRAME);
    auto vspan = [](double y, int& lo, int& hi) {
      lo = std::max(0, (int)py_round(y - PADDLE_H / 2));
      hi = std::min(84, (int)py_round(y + PADDLE_H / 2));
    };
    int lo, hi;
    vspan(enemy_y, lo, hi);
    for (int r = lo; r < hi; ++r)
      std::memset(f + r * 84 + (int)(ENEMY_X - PADDLE_W), 130, (size_t)PADDLE_W);
    vspan(player_y, lo, hi);
    for (int r = lo; r < hi; ++r)
      std::memset(f + r * 84 + (int)PLAYER_X, 150, (size_t)PADDLE_W);
    int by = (int)py_round(ball_y), bx = (int)py_round(ball_x);
    int r0 = std::max(0, by - 1), r1 = std::min(84, by + 1);
    int c0 = std::max(0, bx - 1), c1 = std::min(84, bx + 1);
    for (int r = r0; r < r1; ++r)
      for (int c = c0; c < c1; ++c) f[r * 84 + c] = 236;
  }
};

struct PongBatch {
  int n, hist, act_rep;
  int64_t early_stop;  // 0 = disabled
  std::vector<Game> games;
  std::vector<uint8_t> stacks;  // n * hist * FRAME, chronological order
  std::vector<uint8_t> scratch_prev, scratch_cur;

  PongBatch(int n_, int hist_, int act_rep_, int64_t early_stop_,
            const int64_t* seeds)
      : n(n_), hist(hist_), act_rep(act_rep_), early_stop(early_stop_) {
    games.reserve(n);
    for (int i = 0; i < n; ++i) games.emplace_back((uint64_t)seeds[i]);
    stacks.assign((size_t)n * hist * FRAME, 0);
    scratch_prev.resize(FRAME);
    scratch_cur.resize(FRAME);
  }

  uint8_t* stack(int i) { return stacks.data() + (size_t)i * hist * FRAME; }

  void fill_stack(int i) {  // reset: stack filled with the first frame
    uint8_t* s = stack(i);
    games[i].draw(s);
    for (int k = 1; k < hist; ++k) std::memcpy(s + k * FRAME, s, FRAME);
  }

  void push_frame(int i, const uint8_t* frame) {
    uint8_t* s = stack(i);
    std::memmove(s, s + FRAME, (size_t)(hist - 1) * FRAME);
    std::memcpy(s + (size_t)(hist - 1) * FRAME, frame, FRAME);
  }

  // one agent step of env i; obs/final_obs are hist*FRAME slots
  void step_one(int i, int action, uint8_t* obs, float* reward,
                uint8_t* terminal, uint8_t* truncated, uint8_t* final_obs,
                int32_t* score) {
    Game& g = games[i];
    double move = MOVE[((action % 6) + 6) % 6];
    double rew = 0.0;
    bool have_prev = act_rep >= 2;
    for (int k = 0; k < act_rep; ++k) {
      rew += g.tick(move);
      if (k == act_rep - 2) g.draw(scratch_prev.data());
    }
    g.draw(scratch_cur.data());
    if (have_prev)
      for (int p = 0; p < FRAME; ++p)
        scratch_cur[p] = std::max(scratch_cur[p], scratch_prev[p]);
    push_frame(i, scratch_cur.data());
    g.episode_steps += 1;

    bool term = std::max(g.score_enemy, g.score_player) >= WIN_SCORE;
    // truncation is independent of the game ending this step — the Python
    // path (envs/base.py step) flags the budget hit unconditionally, and
    // recurrent actors read it to pick bootstrap-vs-terminal targets
    bool trunc = early_stop > 0 && g.episode_steps >= early_stop;
    *reward = (float)rew;
    *terminal = (uint8_t)(term || trunc);
    *truncated = (uint8_t)trunc;
    score[0] = g.score_enemy;
    score[1] = g.score_player;
    if (term || trunc) {
      std::memcpy(final_obs, stack(i), (size_t)hist * FRAME);
      g.reset();
      fill_stack(i);
    }
    std::memcpy(obs, stack(i), (size_t)hist * FRAME);
  }
};

}  // namespace

extern "C" {

PongBatch* pong_create(int n, int hist, int act_rep, int64_t early_stop,
                       const int64_t* seeds) {
  if (n <= 0 || hist <= 0 || act_rep <= 0) return nullptr;
  return new PongBatch(n, hist, act_rep, early_stop, seeds);
}

void pong_destroy(PongBatch* pb) { delete pb; }

// obs: (n, hist, 84, 84) uint8
void pong_reset(PongBatch* pb, uint8_t* obs) {
  for (int i = 0; i < pb->n; ++i) {
    pb->games[i].reset();
    pb->fill_stack(i);
    std::memcpy(obs + (size_t)i * pb->hist * FRAME, pb->stack(i),
                (size_t)pb->hist * FRAME);
  }
}

// actions: (n,) int32; obs/final_obs: (n, hist, 84, 84) uint8;
// rewards: (n,) float32; terminals/truncateds: (n,) uint8; scores: (n, 2) int32
void pong_step(PongBatch* pb, const int32_t* actions, uint8_t* obs,
               float* rewards, uint8_t* terminals, uint8_t* truncateds,
               uint8_t* final_obs, int32_t* scores) {
  for (int i = 0; i < pb->n; ++i)
    pb->step_one(i, actions[i], obs + (size_t)i * pb->hist * FRAME,
                 rewards + i, terminals + i, truncateds + i,
                 final_obs + (size_t)i * pb->hist * FRAME, scores + 2 * i);
}

// state layout (10 doubles): player_y, enemy_y, ball_x, ball_y, ball_vx,
// ball_vy, score_enemy, score_player, episode_steps, rng_state — the FULL
// per-game state, so restore resumes the exact trajectory (truncation clock
// and the RNG stream included).  rng_state is a uint64 stored through a
// bit-cast; doubles hold it losslessly.
int pong_state_size() { return 10; }

void pong_get_state(PongBatch* pb, int i, double* buf) {
  const Game& g = pb->games[i];
  buf[0] = g.player_y; buf[1] = g.enemy_y;
  buf[2] = g.ball_x;   buf[3] = g.ball_y;
  buf[4] = g.ball_vx;  buf[5] = g.ball_vy;
  buf[6] = g.score_enemy; buf[7] = g.score_player;
  buf[8] = (double)g.episode_steps;
  std::memcpy(&buf[9], &g.rng.s, sizeof(double));
}

void pong_set_state(PongBatch* pb, int i, const double* buf) {
  Game& g = pb->games[i];
  g.player_y = buf[0]; g.enemy_y = buf[1];
  g.ball_x = buf[2];   g.ball_y = buf[3];
  g.ball_vx = buf[4];  g.ball_vy = buf[5];
  g.score_enemy = (int)buf[6]; g.score_player = (int)buf[7];
  g.episode_steps = (int64_t)buf[8];
  std::memcpy(&g.rng.s, &buf[9], sizeof(double));
}

// render env i's CURRENT raw frame (no stack update) — for equivalence tests
void pong_render(PongBatch* pb, int i, uint8_t* frame) {
  pb->games[i].draw(frame);
}

}  // extern "C"
