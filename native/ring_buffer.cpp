// Lock-free shared-memory transition ring buffer.
//
// Native replacement for the Python shared-memory replay's data plane: the
// reference serialises every feed/sample behind ONE process-wide lock
// (reference core/memories/shared_memory.py:37,69-75), which caps actor
// fan-out; this ring takes the lock away entirely:
//
//   - writers claim slots with one atomic fetch_add on the write cursor
//     (multi-producer, no CAS loops, no blocking);
//   - each row carries a seqlock word: odd while a writer is copying, bumped
//     to the next even value when done; readers copy the row and re-check
//     the word, retrying on a torn read (single-digit-ns overhead in the
//     common case, never blocking writers);
//   - the region lives in POSIX shared memory created by Python
//     (multiprocessing.shared_memory), so any process that attaches by name
//     addresses the same pages — the same topology as the reference's
//     .share_memory_() tensors, without its lock.
//
// Row = the six-field transition schema packed back-to-back
// (state0 | action | reward | gamma_n | state1 | terminal1), exactly the
// flat-array layout of reference shared_memory.py:19-28.
//
// Memory layout of the region:
//   Header (64B aligned): magic, capacity, row_bytes, atomic u64 cursor
//   seq[]: one atomic u32 per row (padded to 64B)
//   data[]: capacity * row_bytes
//
// Build: g++ -O3 -shared -fPIC (driven by native/build.py at import).

#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t MAGIC = 0x52494e47425546ULL;  // "RINGBUF"
constexpr uint64_t ALIGN = 64;

struct Header {
    uint64_t magic;
    uint64_t capacity;
    uint64_t row_bytes;
    std::atomic<uint64_t> cursor;  // total rows ever written
    char pad[ALIGN - 4 * sizeof(uint64_t)];
};
static_assert(sizeof(Header) == ALIGN, "header must stay one cache line");

inline uint64_t align_up(uint64_t x) { return (x + ALIGN - 1) & ~(ALIGN - 1); }

inline Header* header(void* base) { return reinterpret_cast<Header*>(base); }

inline std::atomic<uint32_t>* seqs(void* base) {
    return reinterpret_cast<std::atomic<uint32_t>*>(
        static_cast<char*>(base) + sizeof(Header));
}

inline char* data(void* base, uint64_t capacity) {
    return static_cast<char*>(base) + sizeof(Header)
        + align_up(capacity * sizeof(uint32_t));
}

}  // namespace

extern "C" {

// Total bytes the shared region needs for a given geometry.
uint64_t rb_region_bytes(uint64_t capacity, uint64_t row_bytes) {
    return sizeof(Header) + align_up(capacity * sizeof(uint32_t))
        + capacity * row_bytes;
}

void rb_init(void* base, uint64_t capacity, uint64_t row_bytes) {
    Header* h = header(base);
    h->magic = MAGIC;
    h->capacity = capacity;
    h->row_bytes = row_bytes;
    h->cursor.store(0, std::memory_order_relaxed);
    std::atomic<uint32_t>* s = seqs(base);
    for (uint64_t i = 0; i < capacity; ++i)
        s[i].store(0, std::memory_order_relaxed);
}

int rb_check(void* base, uint64_t capacity, uint64_t row_bytes) {
    Header* h = header(base);
    return h->magic == MAGIC && h->capacity == capacity
        && h->row_bytes == row_bytes;
}

// rows ever written (monotonic feed counter)
uint64_t rb_total(void* base) {
    return header(base)->cursor.load(std::memory_order_acquire);
}

// valid rows available for sampling (<= capacity)
uint64_t rb_size(void* base) {
    Header* h = header(base);
    uint64_t t = h->cursor.load(std::memory_order_acquire);
    return t < h->capacity ? t : h->capacity;
}

// Feed n contiguous rows (n * row_bytes at `rows`).  Lock-free multi-writer:
// each call claims a contiguous index range with one fetch_add; rows wrap
// independently.
void rb_feed(void* base, const void* rows, uint64_t n) {
    Header* h = header(base);
    const uint64_t cap = h->capacity;
    const uint64_t rb = h->row_bytes;
    uint64_t start = h->cursor.fetch_add(n, std::memory_order_acq_rel);
    std::atomic<uint32_t>* s = seqs(base);
    char* d = data(base, cap);
    const char* src = static_cast<const char*>(rows);
    for (uint64_t k = 0; k < n; ++k) {
        uint64_t i = (start + k) % cap;
        // seqlock write: odd = in progress
        uint32_t v = s[i].load(std::memory_order_relaxed);
        s[i].store(v + 1, std::memory_order_release);
        std::atomic_thread_fence(std::memory_order_release);
        std::memcpy(d + i * rb, src + k * rb, rb);
        std::atomic_thread_fence(std::memory_order_release);
        s[i].store(v + 2, std::memory_order_release);
    }
}

// Copy `n` rows at `indices` into `out`, each a consistent (untorn)
// snapshot: re-read on seqlock mismatch.  Returns the number of retries
// (diagnostic; 0 almost always).
uint64_t rb_sample(void* base, const uint64_t* indices, uint64_t n,
                   void* out) {
    Header* h = header(base);
    const uint64_t cap = h->capacity;
    const uint64_t rb = h->row_bytes;
    std::atomic<uint32_t>* s = seqs(base);
    char* d = data(base, cap);
    char* o = static_cast<char*>(out);
    uint64_t retries = 0;
    for (uint64_t k = 0; k < n; ++k) {
        uint64_t i = indices[k];
        for (;;) {
            uint32_t before = s[i].load(std::memory_order_acquire);
            if (before & 1u) {  // write in progress
                ++retries;
                continue;
            }
            std::atomic_thread_fence(std::memory_order_acquire);
            std::memcpy(o + k * rb, d + i * rb, rb);
            std::atomic_thread_fence(std::memory_order_acquire);
            uint32_t after = s[i].load(std::memory_order_acquire);
            if (before == after) break;
            ++retries;
        }
    }
    return retries;
}

}  // extern "C"
