// Bilinear uint8 image resize — the frame-preprocessing kernel of the
// Atari pipeline (reference core/envs/atari_env.py:53-58 resizes the
// grayscale screen to 84x84 with cv2.INTER_LINEAR; this removes the
// OpenCV dependency with a first-party implementation).
//
// Convention: pixel-center alignment (the cv2.INTER_LINEAR convention) —
// src coordinate of output pixel i is (i + 0.5) * (in/out) - 0.5, clamped
// into the source, interpolated in double, rounded half-up to uint8.
// pytorch_distributed_tpu/utils/image.py holds the bit-identical numpy
// reference the tests pin this against.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

inline double clampd(double v, double lo, double hi) {
  return v < lo ? lo : (v > hi ? hi : v);
}

struct Axis {
  std::vector<int> i0, i1;
  std::vector<double> frac;
  Axis(int in, int out) : i0(out), i1(out), frac(out) {
    const double scale = (double)in / out;
    for (int i = 0; i < out; ++i) {
      double s = clampd((i + 0.5) * scale - 0.5, 0.0, in - 1.0);
      int lo = (int)std::floor(s);
      i0[i] = lo;
      i1[i] = std::min(lo + 1, in - 1);
      frac[i] = s - lo;
    }
  }
};

void resize_one(const uint8_t* src, int h, int w, uint8_t* dst,
                const Axis& ay, const Axis& ax, int oh, int ow) {
  for (int y = 0; y < oh; ++y) {
    const uint8_t* r0 = src + ay.i0[y] * w;
    const uint8_t* r1 = src + ay.i1[y] * w;
    const double fy = ay.frac[y];
    uint8_t* out = dst + y * ow;
    for (int x = 0; x < ow; ++x) {
      const double fx = ax.frac[x];
      const double top = r0[ax.i0[x]] * (1.0 - fx) + r0[ax.i1[x]] * fx;
      const double bot = r1[ax.i0[x]] * (1.0 - fx) + r1[ax.i1[x]] * fx;
      out[x] = (uint8_t)(top * (1.0 - fy) + bot * fy + 0.5);
    }
  }
}

}  // namespace

extern "C" {

// src: (n, h, w) uint8 contiguous; dst: (n, oh, ow) uint8
void resize_bilinear_u8(const uint8_t* src, int n, int h, int w,
                        uint8_t* dst, int oh, int ow) {
  if (n <= 0 || h <= 0 || w <= 0 || oh <= 0 || ow <= 0) return;
  Axis ay(h, oh), ax(w, ow);
  for (int i = 0; i < n; ++i)
    resize_one(src + (size_t)i * h * w, h, w,
               dst + (size_t)i * oh * ow, ay, ax, oh, ow);
}

}  // extern "C"
