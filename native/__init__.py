"""First-party native (C++) components.

The reference ships zero first-party native code — all its native
capability is third-party wheels (SURVEY.md §2 "native components").  This
package holds the TPU framework's own native runtime pieces, compiled
on demand with the system toolchain (build.py) and bound via ctypes:

- ring_buffer.cpp — lock-free shared-memory transition ring
  (memory/native_ring.py binding)
- env_pool.cpp — batched C++ env stepper (envs/native_pool.py binding)
"""
