"""Build-on-import for the native components.

The image bans pip/apt installs and ships no pybind11, so native code is
plain C++ compiled with the baked-in g++ into a shared object loaded via
ctypes.  The .so is cached next to the source and rebuilt only when the
source is newer (mtime check); concurrent builders race benignly through an
atomic rename.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str, *, flags: Optional[list] = None,
                  timeout: float = 120.0) -> str:
    """Compile native/{name}.cpp -> native/build/lib{name}.so; returns the
    .so path.  Raises NativeBuildError if the toolchain is unusable (callers
    fall back to the pure-Python path)."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    out_dir = os.path.join(_NATIVE_DIR, "build")
    os.makedirs(out_dir, exist_ok=True)
    so = os.path.join(out_dir, f"lib{name}.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
    os.close(fd)
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           "-o", tmp, src] + (flags or [])
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
    except (OSError, subprocess.TimeoutExpired) as e:  # no g++ / hang
        raise NativeBuildError(f"native build unavailable: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"g++ failed for {name}:\n{proc.stderr[-2000:]}")
    os.replace(tmp, so)  # atomic under concurrent builds
    return so


def load_library(name: str, *, timeout: float = 120.0) -> ctypes.CDLL:
    return ctypes.CDLL(build_library(name, timeout=timeout))
